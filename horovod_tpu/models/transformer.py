"""Llama-family decoder LM, written for the TPU mesh from day one.

Design (vs. the reference, which has no model code of its own and rides
torchvision/Keras — SURVEY.md §6):

* **Pure-functional params pytree** with per-layer leaves *stacked* on a
  leading ``n_layers`` dim and a ``lax.scan`` over layers: one layer's
  HLO compiled once regardless of depth (compile-time and code-size
  friendly, the standard JAX LM idiom).
* **Megatron-style tensor parallelism by annotation**: attention heads
  and FFN hidden dim sharded over ``tp``; GSPMD inserts the psum pair
  per block. No hand-written collective calls in the model body.
* **FSDP by annotation**: the non-tp dim of every matrix is sharded over
  ``fsdp``; XLA all-gathers params on use and reduce-scatters grads —
  the ZeRO-3 pattern the reference approximates with
  reduce-scatter+allgather hierarchical allreduce
  (``nccl_operations.cc:187-360``).
* **Sequence parallelism**: activations' ``T`` dim sharded over ``sp``;
  attention runs as a ring-attention ``shard_map`` island
  (:mod:`horovod_tpu.parallel.ring_attention`) — manual over ``sp``
  only, GSPMD elsewhere.
* bf16 params/activations, f32 RMSNorm accumulation and loss, RoPE, GQA,
  SwiGLU — Llama-3 shapes supported directly.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horovod_tpu.models import moe as moe_lib
from horovod_tpu.parallel.ring_attention import make_sp_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8          # < n_heads → GQA
    d_ff: int = 1376             # SwiGLU hidden
    max_seq: int = 2048
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16    # params/activations; reductions in f32
    remat: bool = True           # jax.checkpoint each layer (HBM for FLOPs)
    # "dots": save matmul outputs, recompute elementwise (measured ~9%
    # faster than full recompute at d=2048 on v5e); "full": recompute
    # everything (minimum memory).
    remat_policy: str = "dots"
    sp_attention: str = "ring"   # "ring" | "ulysses" | "local" |
                                 # "flash" (Pallas kernel, sp=1) |
                                 # "ring_flash" (Pallas blocks, sp>1)
    # Pallas flash tile sizes (None = derived from the sequence
    # length: sequence-spanning up to 1024 through seq 4096, 512x1024
    # beyond — see ops/flash_attention._default_blocks for the
    # measurements). Explicit values override the derivation.
    flash_block_q: Optional[int] = None
    flash_block_k: Optional[int] = None
    # Layer-scan unroll factor: unrolling lets XLA overlap across layer
    # boundaries (+2-3 MFU points at 8 layers); 1 = rolled (smallest
    # program, fastest compile — the multichip/pp paths keep 1).
    scan_unroll: int = 1
    # jax.checkpoint(prevent_cse=...): False is safe under scan/jit
    # (per the JAX docs) and measures +4 MFU points; True is the
    # conservative default only for historical reasons.
    remat_prevent_cse: bool = False
    # Mixture-of-Experts: n_experts > 0 replaces the dense SwiGLU FFN
    # with an expert-parallel MoE FFN in every layer (experts sharded
    # over the `ep` mesh axis; see models/moe.py).
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # MoE dispatch plane (ISSUE 18): None defers to the
    # HOROVOD_MOE_DISPATCH / HOROVOD_MOE_COMPRESSION env knobs
    # (docs/perf_tuning.md). "island" + a lossy codec routes the
    # dispatch/combine hops through the quantized-alltoall shard_map
    # island in models/moe.py; "gspmd" (the default) or codec "none"
    # keep the exact pre-existing GSPMD einsum path.
    moe_dispatch: Optional[str] = None
    moe_compression: Optional[str] = None

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @classmethod
    def llama3_8b(cls, **kw):
        return cls(vocab_size=128_256, d_model=4096, n_layers=32,
                   n_heads=32, n_kv_heads=8, d_ff=14_336, max_seq=8192,
                   **kw)

    @classmethod
    def tiny(cls, **kw):
        base = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, d_ff=128, max_seq=128)
        base.update(kw)  # any field overridable (llama3_8b-style presets
        return cls(**base)  # hard-pin theirs; tiny is a CI scaffold)

    @property
    def moe(self) -> Optional[moe_lib.MoEConfig]:
        if self.n_experts <= 0:
            return None
        return moe_lib.MoEConfig(n_experts=self.n_experts,
                                 top_k=self.moe_top_k,
                                 capacity_factor=self.moe_capacity_factor)


# ---------------------------------------------------------------------------
# Parameter init + sharding specs
# ---------------------------------------------------------------------------

def param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpec pytree matching :func:`init_params`.

    ``tp`` shards heads / FFN hidden / vocab; ``fsdp`` shards the
    other matrix dim. Layer-stacked leaves carry a leading ``None``
    (the scan dim is never sharded).
    """
    layers: Dict[str, Any] = {
        "attn_norm": P(None, None),    # [L, D]
        "wq": P(None, "fsdp", "tp"),   # [L, D, H*Dh]
        "wk": P(None, "fsdp", "tp"),   # [L, D, Hkv*Dh]
        "wv": P(None, "fsdp", "tp"),
        "wo": P(None, "tp", "fsdp"),   # [L, H*Dh, D]
        "mlp_norm": P(None, None),
    }
    if cfg.moe is not None:
        layers["moe"] = moe_lib.moe_param_specs()
    else:
        layers.update({
            # Separate gate/up/q/k/v matmuls measure FASTER than fused
            # wide projections on v5e at d=2048-4096 (fusion costs the
            # output slices more than the larger tile buys: 42.7% vs
            # 46.3% MFU) — keep the unfused layout.
            "w_gate": P(None, "fsdp", "tp"),  # [L, D, F]
            "w_up": P(None, "fsdp", "tp"),
            "w_down": P(None, "tp", "fsdp"),  # [L, F, D]
        })
    return {
        # [V, D] vocab-parallel; looked up via the explicit shard_map
        # island in :func:`embed_lookup` — a global-view gather on a
        # vocab-sharded table forces GSPMD into "involuntary full
        # rematerialization" (replicate the table, then re-partition).
        "embed": P("tp", "fsdp"),
        "layers": layers,
        "final_norm": P(None),
        "lm_head": P("fsdp", "tp"),        # [D, V]
    }


def init_params(cfg: TransformerConfig, key: jax.Array,
                mesh: Optional[Mesh] = None) -> Dict[str, Any]:
    """Initialise the parameter pytree (optionally already sharded onto
    ``mesh`` so giant models never materialise replicated)."""
    k = iter(jax.random.split(key, 16))
    L, D, H, Hkv, Dh, F, V = (cfg.n_layers, cfg.d_model, cfg.n_heads,
                              cfg.n_kv_heads, cfg.head_dim, cfg.d_ff,
                              cfg.vocab_size)
    dt = cfg.dtype

    def dense(kk, shape, fan_in):
        return (jax.random.normal(kk, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dt)

    layers = {
        "attn_norm": jnp.ones((L, D), dt),
        "wq": dense(next(k), (L, D, H * Dh), D),
        "wk": dense(next(k), (L, D, Hkv * Dh), D),
        "wv": dense(next(k), (L, D, Hkv * Dh), D),
        "wo": dense(next(k), (L, H * Dh, D), H * Dh),
        "mlp_norm": jnp.ones((L, D), dt),
    }
    if cfg.moe is not None:
        layers["moe"] = moe_lib.init_moe_params(next(k), L, D, F, cfg.moe, dt)
    else:
        layers.update({
            "w_gate": dense(next(k), (L, D, F), D),
            "w_up": dense(next(k), (L, D, F), D),
            "w_down": dense(next(k), (L, F, D), F),
        })
    params = {
        "embed": dense(next(k), (V, D), D),
        "layers": layers,
        "final_norm": jnp.ones((D,), dt),
        "lm_head": dense(next(k), (D, V), D),
    }
    if mesh is not None:
        shardings = jax.tree.map(lambda s: NamedSharding(mesh, s),
                                 param_specs(cfg),
                                 is_leaf=lambda x: isinstance(x, P))
        params = jax.device_put(params, shardings)
    return params


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _rmsnorm(x, w, eps):
    h = x.astype(jnp.float32)
    h = h * lax.rsqrt(jnp.mean(h * h, axis=-1, keepdims=True) + eps)
    return (h * w.astype(jnp.float32)).astype(x.dtype)


def _rope(x, pos, theta):
    """Rotary embedding. x: [B, T, H, D]; pos: [T] global positions."""
    d = x.shape[-1]
    inv = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = pos[:, None].astype(jnp.float32) * inv[None, :]      # [T, D/2]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., 0::2], x[..., 1::2]
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    y1 = x1 * cos - x2 * sin
    y2 = x2 * cos + x1 * sin
    y = jnp.stack([y1, y2], axis=-1).reshape(x.shape)
    return y.astype(x.dtype)


def _replicated_table_lookup(embed, tokens, dtype, mesh, codec: str):
    """The table-replication fallback of :func:`embed_lookup`, with the
    replication reshard — the table-sized all-gather the island exists
    to avoid — optionally shipped narrow. ``codec`` "none" is the exact
    pre-existing path (annotated f32/bf16 reshard); "bf16"/"fp16" cast
    the table to the wire dtype before the constraint; "int8" ships
    blockwise q+scales (``ops/quantized.py`` codec, ~4x vs f32) and
    dequantizes only the gathered rows."""
    from jax.sharding import NamedSharding as NS

    from horovod_tpu.ops.quantized import _CAST_WIRE

    if codec in _CAST_WIRE:
        t = lax.with_sharding_constraint(
            embed.astype(_CAST_WIRE[codec]), NS(mesh, P(None, None)))
        return t[tokens].astype(dtype)
    if codec == "int8":
        from horovod_tpu.ops.quantized import (
            blockwise_int8_decode, blockwise_int8_encode)
        q, s = blockwise_int8_encode(embed)
        q = lax.with_sharding_constraint(q, NS(mesh, P(None, None)))
        s = lax.with_sharding_constraint(s, NS(mesh, P(None, None)))
        rows = blockwise_int8_decode(q[tokens], s[tokens], embed.shape[-1])
        return rows.astype(dtype)
    replicated = lax.with_sharding_constraint(
        embed, NS(mesh, P(None, None)))
    return replicated.astype(dtype)[tokens]


def embed_lookup(embed, tokens, dtype, mesh: Optional[Mesh],
                 compression=None):
    """Vocab-parallel embedding lookup (Megatron recipe, TPU island).

    With the table sharded ``P("tp", "fsdp")``, each device holds a
    ``[V/tp, D/fsdp]`` tile. A global-view ``table[tokens]`` forces
    GSPMD to replicate the whole table every step ("involuntary full
    rematerialization", spmd_partitioner.cc) — at Llama-3-8B scale an
    all-gather of a ~1 GB table per step. Instead we run a shard_map
    island manual over ``{tp, fsdp}`` only (dp/sp stay under GSPMD):
    mask out-of-range tokens, gather locally, ``psum`` the partial rows
    over ``tp`` and ``all_gather`` the model dim over ``fsdp`` — all
    collectives are activation-sized, never table-sized.

    Reference analog: none — the reference (torch DDP-style) replicates
    embeddings on every rank; vocab-parallelism is the TPU-first design.

    ``compression`` (a ``hvd.Compression`` member; None = uncompressed)
    narrows the table-replication *fallback* paths below — the case
    where the whole table actually moves every step. The island path
    ignores it: its wires are activation-sized psums/gathers already in
    the model dtype, nothing table-sized to compress.
    """
    from horovod_tpu import compression as compression_lib
    from horovod_tpu.common import jax_compat
    from horovod_tpu.common.jax_compat import shard_map

    codec = compression_lib.in_jit_codec(compression)
    V, D = embed.shape
    tp = mesh.shape.get("tp", 1) if mesh is not None else 1
    fsdp = mesh.shape.get("fsdp", 1) if mesh is not None else 1
    if tp * fsdp == 1:
        return embed.astype(dtype)[tokens]
    if not jax_compat.HAS_NEW_SHARD_MAP:
        # Legacy jax: the partial-manual island lowers axis_index to a
        # PartitionId op the old SPMD partitioner rejects. Take the
        # global-view gather — the table is replicated for the lookup
        # (the cost this island exists to avoid), but EXPLICITLY so:
        # an annotated reshard is a planned all-gather, not the
        # partitioner's "involuntary full rematerialization" red flag.
        return _replicated_table_lookup(embed, tokens, dtype, mesh, codec)
    if V % tp or D % fsdp:
        import warnings
        warnings.warn(
            f"embed_lookup: table [{V}, {D}] not divisible by "
            f"(tp={tp}, fsdp={fsdp}); falling back to a global-view "
            "gather, which forces GSPMD to replicate the table every "
            "step. Pad vocab_size/d_model to multiples of the mesh axes.")
        if codec != "none" and mesh is not None:
            return _replicated_table_lookup(embed, tokens, dtype, mesh,
                                            codec)
        return embed.astype(dtype)[tokens]
    v_loc = V // tp
    # XLA-CPU workaround (same as pipeline.py): shard_map-level bf16
    # psum/reduce-scatter crashes the CPU AllReducePromotion pass; keep
    # island wires f32 on CPU. TPU reduces bf16 natively.
    f32_wire = (jax.default_backend() == "cpu" and dtype == jnp.bfloat16)
    wire = jnp.float32 if f32_wire else dtype

    def island(table, toks):
        start = lax.axis_index("tp") * v_loc
        idx = toks - start
        valid = (idx >= 0) & (idx < v_loc)
        rows = table.astype(wire)[jnp.where(valid, idx, 0)]
        rows = jnp.where(valid[..., None], rows, jnp.zeros((), wire))
        rows = lax.psum(rows, "tp")
        return lax.all_gather(rows, "fsdp", axis=-1, tiled=True)

    # check_vma=False: the VMA checker cannot infer that a tiled
    # all_gather's output is replicated over the gathered axis (same
    # limitation as the ring_flash island in ring_attention.py).
    out = shard_map(island, mesh=mesh,
                    in_specs=(P("tp", "fsdp"), P()), out_specs=P(),
                    axis_names={"tp", "fsdp"}, check_vma=False)(embed, tokens)
    return out.astype(dtype)


def _attention_island(cfg: TransformerConfig, mesh: Optional[Mesh]):
    """attn(q, k, v) — ring/Ulysses shard_map island over ``sp`` when a
    mesh with sp>1 is given, plain attention otherwise (single
    construction point: :func:`~horovod_tpu.parallel.ring_attention.make_sp_attention`)."""
    if mesh is not None and "sp" not in mesh.axis_names:
        mesh = None
    return make_sp_attention(mesh, axis_name="sp", impl=cfg.sp_attention,
                             causal=True, block_q=cfg.flash_block_q,
                             block_k=cfg.flash_block_k)


def remat_policy_fn(cfg: TransformerConfig):
    """jax.checkpoint policy for the layer remat (None = full)."""
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if cfg.remat_policy == "dots_all":
        # Save EVERY matmul output (attention scores included):
        # backward recomputes only elementwise ops — the highest-MFU
        # remat tier when HBM allows (measured +3-4 MFU points over
        # "dots" at d=2048x8L on v5e).
        return jax.checkpoint_policies.dots_saveable
    if cfg.remat_policy == "full":
        return None
    raise ValueError(f"unknown remat_policy {cfg.remat_policy!r}")


def _constrainer(mesh: Optional[Mesh]):
    def constrain(x, *spec):
        if mesh is not None:
            return lax.with_sharding_constraint(
                x, NamedSharding(mesh, P(*spec)))
        return x
    return constrain


def decoder_layer(cfg: TransformerConfig, attend, constrain, x, lp,
                  pos_offset=0, moe_fn=None):
    """One pre-norm decoder block (attention + FFN/MoE) on ``x``
    [B, T, D]; ``lp`` is this layer's param dict (no leading L dim).
    Returns (x, aux_loss) — aux is 0 for dense FFN, the load-balancing
    term for MoE. Module-level so both the layer scan and the pipeline
    stage function build on it.

    ``moe_fn`` overrides the MoE FFN call (``fn(h, lp['moe']) ->
    (y, aux)``): :func:`forward_with_aux` passes the
    :func:`moe_lib.make_moe_ffn`-selected dispatch plane; ``None``
    (pipeline/island callers, which run inside their own manual
    regions) keeps the plain GSPMD :func:`moe_lib.moe_ffn`.

    ``pos_offset`` shifts the rotary positions: callers running this
    layer INSIDE a manual island on a sequence SHARD (pp+sp) pass
    ``axis_index("sp") * local_T`` so every shard embeds its global
    positions; the flat path's T is already global and keeps 0."""
    H, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    B, T = x.shape[0], x.shape[1]
    pos = jnp.arange(T) + pos_offset

    h = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"]).reshape(B, T, H, Dh)
    kk = (h @ lp["wk"]).reshape(B, T, Hkv, Dh)
    vv = (h @ lp["wv"]).reshape(B, T, Hkv, Dh)
    q = _rope(q, pos, cfg.rope_theta)
    kk = _rope(kk, pos, cfg.rope_theta)
    if Hkv != H and not getattr(attend, "handles_gqa", False):
        # GQA: tile kv heads up to H for impls that need square heads
        # (flash reads grouped K/V natively and skips this copy).
        rep = H // Hkv
        kk = jnp.repeat(kk, rep, axis=2)
        vv = jnp.repeat(vv, rep, axis=2)
    o = attend(q, kk, vv).reshape(B, T, H * Dh)
    x = x + (o @ lp["wo"]).astype(cfg.dtype)
    x = constrain(x, ("dp", "fsdp"), "sp", None)

    h = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.moe is not None:
        if moe_fn is None:
            y, aux = moe_lib.moe_ffn(h, lp["moe"], cfg.moe)
        else:
            y, aux = moe_fn(h, lp["moe"])
        x = x + y.astype(cfg.dtype)
    else:
        g = jax.nn.silu((h @ lp["w_gate"]).astype(jnp.float32))
        u = (h @ lp["w_up"]).astype(jnp.float32)
        x = x + ((g * u).astype(cfg.dtype) @ lp["w_down"]).astype(cfg.dtype)
        aux = jnp.zeros((), jnp.float32)
    x = constrain(x, ("dp", "fsdp"), "sp", None)
    return x, aux


def forward_with_aux(params, tokens, cfg: TransformerConfig,
                     mesh: Optional[Mesh] = None):
    """tokens ``[B, T]`` int32 → (logits ``[B, T, V]``, aux_loss).

    With a mesh: activations constrained to ``P(('dp','fsdp'), 'sp')``
    on [B, T] dims; attention heads tp-sharded by GSPMD propagation from
    the weight specs.
    """
    constrain = _constrainer(mesh)
    attend = _attention_island(cfg, mesh)
    moe_fn = (moe_lib.make_moe_ffn(cfg.moe, mesh,
                                   dispatch=cfg.moe_dispatch,
                                   codec=cfg.moe_compression)
              if cfg.moe is not None else None)

    x = embed_lookup(params["embed"], tokens, cfg.dtype, mesh)
    x = constrain(x, ("dp", "fsdp"), "sp", None)

    def layer(x, lp):
        return decoder_layer(cfg, attend, constrain, x, lp,
                             moe_fn=moe_fn)

    if cfg.remat:
        layer = jax.checkpoint(layer, policy=remat_policy_fn(cfg),
                               prevent_cse=cfg.remat_prevent_cse)

    x, auxes = lax.scan(layer, x, params["layers"],
                        unroll=cfg.scan_unroll)
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = x @ params["lm_head"]
    return constrain(logits, ("dp", "fsdp"), "sp", "tp"), auxes.sum()


def forward(params, tokens, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None):
    """tokens ``[B, T]`` int32 → logits ``[B, T, V]`` (cfg.dtype)."""
    return forward_with_aux(params, tokens, cfg, mesh)[0]


def lm_loss(params, batch, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None):
    """Next-token cross-entropy (f32 log-softmax) over ``batch["tokens"]``
    [B, T+1] plus the MoE load-balancing aux term; returns scalar."""
    tokens = batch["tokens"]
    inp, tgt = tokens[:, :-1], tokens[:, 1:]
    logits, aux = forward_with_aux(params, inp, cfg, mesh)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return nll.mean() + aux


# ---------------------------------------------------------------------------
# Train step factory
# ---------------------------------------------------------------------------

def make_train_step(cfg: TransformerConfig, mesh: Mesh, optimizer=None, *,
                    compression=None):
    """Build ``(init_state, step)``: a jitted SPMD training step over
    ``mesh`` — grads by ``jax.grad`` with GSPMD-inserted collectives
    (tp psums, fsdp reduce-scatters, dp allreduces all ride ICI), optax
    update, donated state.

    The Horovod-product analog of ``DistributedOptimizer`` +
    fused allreduce (``torch/optimizer.py:128``, ``operations.cc:943``)
    collapsed into one compiled program.

    ``compression`` (a ``hvd.Compression`` member; None/none = the
    exact pre-existing GSPMD step, bitwise unchanged) opts the
    data-plane gradient collectives into the quantized in-jit path
    (EQuARX). On a dp-only mesh the step is rebuilt as a ``shard_map``
    over ``dp`` with the model replicated per shard and gradients
    reduced by the blockwise int8/bf16 reduce-scatter + all-gather of
    ``ops/quantized.py``, int8 with rank-local error-feedback residuals
    carried in ``state["ef"]``. On a mesh with ``fsdp > 1`` the step
    becomes the partial-manual fsdp island
    (:func:`_make_fsdp_quantized_train_step`): params stay
    fsdp-sharded, the gradient reduce-scatter ships ``codec``-narrow
    bytes, and a second quantized hop covers ``dp`` when present.
    Scope: dp and fsdp are the gradient planes — tp/sp/pp/ep sharding
    has no gradient collective to intercept under GSPMD, so meshes with
    those axes > 1 raise.
    """
    import optax
    if optimizer is None:
        optimizer = optax.adamw(3e-4, weight_decay=0.01)

    from horovod_tpu import compression as compression_lib
    codec = compression_lib.in_jit_codec(compression)
    if codec != "none":
        return _make_quantized_train_step(cfg, mesh, optimizer,
                                          compression, codec)

    specs = param_specs(cfg)

    def init_state(key):
        params = init_params(cfg, key, mesh)
        opt_state = optimizer.init(params)
        return {"params": params, "opt": opt_state, "step": jnp.zeros((), jnp.int32)}

    def step(state, batch):
        loss, grads = jax.value_and_grad(lm_loss)(
            state["params"], batch, cfg, mesh)
        updates, new_opt = optimizer.update(
            grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {"params": params, "opt": new_opt,
                "step": state["step"] + 1}, loss

    param_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                            is_leaf=lambda x: isinstance(x, P))
    batch_sh = {"tokens": NamedSharding(mesh, P(("dp", "fsdp"), None))}

    # Donating state needs the compiler to alias in/out buffers; with
    # inferred out_shardings legacy XLA can pick a different output
    # sharding and abort with an aliasing size mismatch (modern jax
    # reshards around the alias). Skip donation there — compat mode
    # pays one state copy per step, correctness first.
    from horovod_tpu.common import jax_compat
    donate = (0,) if jax_compat.HAS_NEW_SHARD_MAP else ()
    jit_step = jax.jit(step, donate_argnums=donate,
                       in_shardings=(None, batch_sh),
                       out_shardings=(None, NamedSharding(mesh, P())))
    return init_state, jit_step, param_sh


def _make_quantized_train_step(cfg: TransformerConfig, mesh: Mesh,
                               optimizer, compression, codec: str):
    """The ``compression=`` dispatcher of :func:`make_train_step`.

    Routes to the dp shard_map step (PR 9, byte-identical to before)
    or — when the mesh carries ``fsdp > 1`` — to the fsdp island
    below. Every other sharded axis raises: tp/sp/pp/ep collectives
    are activation-sized psums GSPMD inserts in the middle of the
    model, not gradient hops a codec could ride.
    """
    bad = [(ax, sz) for ax, sz in mesh.shape.items()
           if ax not in ("dp", "fsdp") and sz > 1]
    if bad:
        raise ValueError(
            f"make_train_step(compression={codec!r}) quantizes the "
            f"data-parallel gradient allreduce and the fsdp gradient "
            f"reduce-scatter; mesh axes {bad} have no explicit gradient "
            "collective to intercept under GSPMD. Use a dp/fsdp mesh, "
            "or compression=None for the GSPMD-sharded step.")
    if "dp" not in mesh.shape and mesh.shape.get("fsdp", 1) <= 1:
        raise ValueError(
            f"compression= needs a data axis ('dp', or 'fsdp' > 1); "
            f"mesh has {dict(mesh.shape)}")
    if mesh.shape.get("fsdp", 1) > 1:
        return _make_fsdp_quantized_train_step(cfg, mesh, optimizer,
                                               compression, codec)
    return _make_dp_quantized_train_step(cfg, mesh, optimizer,
                                         compression, codec)


def _make_dp_quantized_train_step(cfg: TransformerConfig, mesh: Mesh,
                                  optimizer, compression, codec: str):
    """The dp-only ``compression=`` body of :func:`make_train_step`.

    The GSPMD step has no interceptable dp gradient collective
    (autodiff of the global-mean loss reduces implicitly), so this
    variant makes the gradient plane explicit: one ``shard_map`` over
    the whole mesh runs the model replicated per dp shard on its local
    batch slice and reduces gradients with
    :func:`~horovod_tpu.ops.quantized.quantized_allreduce` — both hops
    of every gradient leaf ship ``codec``-narrow bytes, and int8
    threads per-rank error-feedback residuals as ``state["ef"]``
    leaves (globally ``[dp, *param.shape]`` f32, sharded ``P("dp")``,
    exactly the host plane's per-rank EF-slab shape discipline).
    """
    import optax

    from horovod_tpu import compression as compression_lib
    from horovod_tpu.common.jax_compat import shard_map
    from horovod_tpu.common.ops_enum import Average
    from horovod_tpu.ops.quantized import quantized_allreduce

    ndp = mesh.shape["dp"]
    use_ef = compression_lib.needs_error_feedback(compression)

    def init_state(key):
        # Params replicated over dp (a dp-only mesh has no model
        # sharding; param_specs' tp/fsdp axes may not even exist here).
        params = jax.device_put(init_params(cfg, key, None),
                                NamedSharding(mesh, P()))
        opt_state = optimizer.init(params)
        state = {"params": params, "opt": opt_state,
                 "step": jnp.zeros((), jnp.int32)}
        if use_ef:
            state["ef"] = jax.tree.map(
                lambda p: jnp.zeros((ndp,) + p.shape, jnp.float32), params)
        return state

    def shard_step(params, opt, ef, tokens):
        # Per dp shard: local batch slice, model built mesh-free (all
        # sharded axes are manual here; there is no GSPMD inside).
        def loss_fn(p):
            return lm_loss(p, {"tokens": tokens}, cfg, None)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        leaves, treedef = jax.tree.flatten(grads)
        if use_ef:
            ef_leaves = jax.tree.flatten(ef)[0]
            red, nef = [], []
            for g, r in zip(leaves, ef_leaves):
                y, nr = quantized_allreduce(g, op=Average, axis_name="dp",
                                            codec=codec, residual=r[0])
                red.append(y)
                nef.append(nr[None])
            grads = jax.tree.unflatten(treedef, red)
            ef = jax.tree.unflatten(treedef, nef)
        else:
            grads = jax.tree.unflatten(treedef, [
                quantized_allreduce(g, op=Average, axis_name="dp",
                                    codec=codec) for g in leaves])
        loss = lax.pmean(loss, "dp")
        # Identical (all-gathered) reduced grads on every shard ->
        # the replicated update keeps params bitwise in sync.
        updates, opt = optimizer.update(grads, opt, params)
        params = optax.apply_updates(params, updates)
        return params, opt, ef, loss

    smapped = shard_map(
        shard_step, mesh=mesh,
        in_specs=(P(), P(), P("dp"), P("dp")),
        out_specs=(P(), P(), P("dp"), P()))

    def step(state, batch):
        params, opt, ef, loss = smapped(
            state["params"], state["opt"], state.get("ef", {}),
            batch["tokens"])
        new_state = {"params": params, "opt": opt,
                     "step": state["step"] + 1}
        if use_ef:
            new_state["ef"] = ef
        return new_state, loss

    param_sh = jax.tree.map(lambda s: NamedSharding(mesh, P()),
                            param_specs(cfg),
                            is_leaf=lambda x: isinstance(x, P))
    return init_state, jax.jit(step), param_sh


def _fsdp_spec_dim(spec) -> Optional[int]:
    """Index of the ``fsdp``-sharded dimension in a PartitionSpec
    (None for fsdp-replicated leaves like the norms)."""
    for i, entry in enumerate(spec):
        if entry == "fsdp" or (isinstance(entry, tuple) and "fsdp" in entry):
            return i
    return None


def _make_fsdp_quantized_train_step(cfg: TransformerConfig, mesh: Mesh,
                                    optimizer, compression, codec: str):
    """The fsdp ``compression=`` body of :func:`make_train_step`.

    GSPMD's fsdp plane reduce-scatters gradients and all-gathers
    params with collectives it inserts itself — there is no hop a
    codec can ride. This variant expresses the fsdp step as a
    partial-manual ``shard_map`` island (manual over the data axes
    ``{dp, fsdp}``; on legacy jax the island is spelled full-manual,
    exactly the generation gate the embed island uses — legal here
    because every non-data axis is size 1, which the dispatcher
    enforces):

    * params stay fsdp-sharded on their ``param_specs`` dims (the
      ZeRO-3 layout; optimizer state and EF residuals shard with
      them), entering the island as local shards;
    * the forward all-gathers each sharded leaf over ``fsdp`` in the
      model dtype (the standard ZeRO param gather — already ≤ bf16
      for bf16 models, deliberately not lossy-quantized: param error
      has no EF to telescope through);
    * the gradient reduce-scatter is the explicit
      :func:`~horovod_tpu.ops.quantized.quantized_reduce_scatter`
      hop — quantize per destination shard → ``all_to_all`` →
      multiply-only f32 fold (psum_scatter-native for bf16/fp16 where
      the backend allows, per the jax_compat probe); fsdp-replicated
      leaves (norms) ride a full ``quantized_allreduce`` over fsdp;
    * when the mesh also carries ``dp > 1``, a second
      ``quantized_allreduce`` hop over ``dp`` reduces each gradient
      shard across data-parallel groups (the requantize point — its
      hop-2 re-encode + narrow all-gather);
    * int8 error-feedback residuals are optimizer-state leaves
      ``state["ef"] = {"fsdp": ..., "dp": ...}``, leading dims
      ``[dp, fsdp]`` sharded ``P("dp", "fsdp")`` — per-rank slabs,
      the same contract as the dp path — with the dp-hop residuals
      shard-shaped (they compensate the post-scatter hop);
    * the optimizer update runs OUTSIDE the island on the sharded
      trees (pure elementwise; GSPMD keeps every leaf on its shard).
    """
    import optax

    from horovod_tpu import compression as compression_lib
    from horovod_tpu.common import jax_compat
    from horovod_tpu.common.jax_compat import shard_map
    from horovod_tpu.common.ops_enum import Average
    from horovod_tpu.ops.quantized import (quantized_allreduce,
                                           quantized_reduce_scatter)

    nfsdp = mesh.shape["fsdp"]
    ndp = mesh.shape.get("dp", 1)
    dp_hop = ndp > 1
    batch_axes = tuple(ax for ax in ("dp", "fsdp") if ax in mesh.shape)
    lead = len(batch_axes)
    world_shape = tuple(mesh.shape[ax] for ax in batch_axes)
    use_ef = compression_lib.needs_error_feedback(compression)
    specs = param_specs(cfg)

    def _island_spec(spec):
        d = _fsdp_spec_dim(spec)
        return P(*[("fsdp" if i == d else None) for i in range(len(spec))])

    isl_specs = jax.tree.map(_island_spec, specs,
                             is_leaf=lambda x: isinstance(x, P))

    # Shard divisibility is a build-time contract (shard_map cannot pad
    # the way GSPMD does): every fsdp-sharded dim must divide by nfsdp.
    shapes = jax.eval_shape(lambda k: init_params(cfg, k, None),
                            jax.ShapeDtypeStruct((2,), jnp.uint32))
    bad = []

    def _check_divisible(path, leaf, spec):
        d = _fsdp_spec_dim(spec)
        if d is not None and leaf.shape[d] % nfsdp:
            bad.append(f"{jax.tree_util.keystr(path)}{leaf.shape} dim {d}")
    jax.tree_util.tree_map_with_path(_check_divisible, shapes, specs)
    if bad:
        raise ValueError(
            f"make_train_step(compression={codec!r}): fsdp={nfsdp} does "
            f"not divide the sharded dim of {bad}; pad the model dims "
            "to multiples of the fsdp axis (the GSPMD path pads "
            "implicitly, the manual island cannot).")

    def island(p_shards, ef, tokens):
        params = jax.tree.map(
            lambda x, s: (lax.all_gather(x, "fsdp", axis=_fsdp_spec_dim(s),
                                         tiled=True)
                          if _fsdp_spec_dim(s) is not None else x),
            p_shards, specs)

        def loss_fn(p):
            return lm_loss(p, {"tokens": tokens}, cfg, None)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        g_leaves, treedef = jax.tree.flatten(grads)
        s_leaves = jax.tree.flatten(
            specs, is_leaf=lambda x: isinstance(x, P))[0]
        idx = (0,) * lead
        expand = (None,) * lead
        rs_res = (jax.tree.flatten(ef["fsdp"])[0] if use_ef
                  else [None] * len(g_leaves))
        dp_res = (jax.tree.flatten(ef["dp"])[0] if use_ef and dp_hop
                  else [None] * len(g_leaves))
        out, new_rs, new_dp = [], [], []
        for g, s, r1, r2 in zip(g_leaves, s_leaves, rs_res, dp_res):
            d = _fsdp_spec_dim(s)
            r1l = r1[idx] if r1 is not None else None
            if d is None:
                y = quantized_allreduce(g, op=Average, axis_name="fsdp",
                                        codec=codec, residual=r1l)
            else:
                y = quantized_reduce_scatter(g, op=Average,
                                             axis_name="fsdp", codec=codec,
                                             axis=d, residual=r1l)
            if r1l is not None:
                y, nr1 = y
                new_rs.append(nr1[expand])
            if dp_hop:
                r2l = r2[idx] if r2 is not None else None
                y = quantized_allreduce(y, op=Average, axis_name="dp",
                                        codec=codec, residual=r2l)
                if r2l is not None:
                    y, nr2 = y
                    new_dp.append(nr2[expand])
            out.append(y)
        grads = jax.tree.unflatten(treedef, out)
        new_ef = {}
        if use_ef:
            new_ef["fsdp"] = jax.tree.unflatten(treedef, new_rs)
            if dp_hop:
                new_ef["dp"] = jax.tree.unflatten(treedef, new_dp)
        for ax in batch_axes:
            loss = lax.pmean(loss, ax)
        return loss, grads, new_ef

    # Modern jax: a genuine partial-manual island — only the data axes
    # are manual, anything else rides auto/GSPMD. Legacy jax cannot
    # lower partial-manual (axis_index becomes a PartitionId op the old
    # partitioner rejects — the embed-island gate), so the island is
    # full-manual there; the dispatcher guarantees the remaining axes
    # are size 1, which full-manual handles trivially.
    axis_names = ({"dp", "fsdp"} & set(mesh.axis_names)
                  if jax_compat.HAS_NEW_SHARD_MAP else None)
    # check_vma=False: the VMA checker cannot infer a tiled
    # all_gather's output is replicated over the gathered axis (same
    # limitation as the embed island).
    smapped = shard_map(
        island, mesh=mesh,
        in_specs=(isl_specs, P(*batch_axes), P(batch_axes)),
        out_specs=(P(), isl_specs, P(*batch_axes)),
        axis_names=axis_names, check_vma=False)

    def init_state(key):
        shardings = jax.tree.map(lambda s: NamedSharding(mesh, s),
                                 isl_specs,
                                 is_leaf=lambda x: isinstance(x, P))
        params = jax.device_put(init_params(cfg, key, None), shardings)
        opt_state = optimizer.init(params)
        state = {"params": params, "opt": opt_state,
                 "step": jnp.zeros((), jnp.int32)}
        if use_ef:
            def z_full(p):
                return jnp.zeros(world_shape + p.shape, jnp.float32)

            def z_shard(p, s):
                d = _fsdp_spec_dim(s)
                shp = list(p.shape)
                if d is not None:
                    shp[d] //= nfsdp
                return jnp.zeros(world_shape + tuple(shp), jnp.float32)

            ef = {"fsdp": jax.tree.map(z_full, params)}
            if dp_hop:
                ef["dp"] = jax.tree.map(z_shard, params, specs)
            state["ef"] = ef
        return state

    def step(state, batch):
        loss, grads, new_ef = smapped(state["params"],
                                      state.get("ef", {}),
                                      batch["tokens"])
        updates, new_opt = optimizer.update(grads, state["opt"],
                                            state["params"])
        params = optax.apply_updates(state["params"], updates)
        new_state = {"params": params, "opt": new_opt,
                     "step": state["step"] + 1}
        if use_ef:
            new_state["ef"] = new_ef
        return new_state, loss

    param_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), isl_specs,
                            is_leaf=lambda x: isinstance(x, P))
    return init_state, jax.jit(step), param_sh
