"""ResNet v1.5 (50/101) in Flax — the benchmark workhorse.

The reference benchmarks Horovod with torchvision/Keras ResNet-50
(``examples/pytorch/pytorch_synthetic_benchmark.py:29``,
``docs/benchmarks.rst:17-43``); a standalone TPU framework needs its
own. Written MXU-first: bf16 convs (f32 variance accumulation in BN),
NHWC layout (TPU conv native), no data-dependent shapes.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16


class Bottleneck(nn.Module):
    filters: int
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        bn = partial(nn.BatchNorm, use_running_average=not train,
                     momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(bn()(y))
        # v1.5: stride lives on the 3x3, not the 1x1
        y = conv(self.filters, (3, 3), strides=(self.strides,) * 2)(y)
        y = nn.relu(bn()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = bn(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides,) * 2,
                            name="proj")(residual)
            residual = bn(name="proj_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.cfg
        x = x.astype(cfg.dtype)
        x = nn.Conv(cfg.width, (7, 7), strides=(2, 2), padding=[(3, 3)] * 2,
                    use_bias=False, dtype=cfg.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=cfg.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1)] * 2)
        for i, n_blocks in enumerate(cfg.stage_sizes):
            for j in range(n_blocks):
                x = Bottleneck(cfg.width * 2 ** i,
                               strides=2 if i > 0 and j == 0 else 1,
                               dtype=cfg.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(cfg.num_classes, dtype=jnp.float32)(x)
        return x


def resnet50(num_classes: int = 1000, dtype=jnp.bfloat16) -> ResNet:
    return ResNet(ResNetConfig((3, 4, 6, 3), num_classes, dtype=dtype))


def resnet101(num_classes: int = 1000, dtype=jnp.bfloat16) -> ResNet:
    return ResNet(ResNetConfig((3, 4, 23, 3), num_classes, dtype=dtype))
