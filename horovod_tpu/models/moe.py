"""Mixture-of-Experts FFN with expert parallelism over the ``ep`` mesh
axis.

The reference has no MoE (its only relevant primitive is alltoall,
``horovod/common/operations.cc:1131`` — SURVEY.md §2.6 explicitly maps
MoE expert dispatch onto it). The TPU-native design is the GShard
dense-dispatch formulation: routing builds one-hot dispatch/combine
tensors and the expert dimension is *sharded over* ``ep``, so GSPMD
lowers the two dispatch einsums to ICI all-to-alls — no hand-written
collectives, fully fused by XLA, and differentiable end to end.

Shapes (per layer): tokens ``[B, T, D]``, experts ``E``, per-group
capacity ``C = ceil(k · T · capacity_factor / E)`` with groups = batch
rows. Top-k (default 2) gating with the standard load-balancing
auxiliary loss (Switch/GShard form).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01


def capacity(cfg: MoEConfig, seq_len: int) -> int:
    return max(1, math.ceil(cfg.top_k * seq_len * cfg.capacity_factor
                            / cfg.n_experts))


def moe_param_specs(n_layers_leading: bool = True) -> Dict[str, Any]:
    """PartitionSpecs for one MoE FFN block (leading ``L`` dim when
    stacked for the layer scan): experts over ``ep``, matrix dims over
    ``fsdp``/``tp`` like the dense FFN."""
    lead = (None,) if n_layers_leading else ()
    return {
        "router": P(*lead, None, None),           # [L?, D, E] replicated
        "w_gate": P(*lead, "ep", "fsdp", "tp"),   # [L?, E, D, F]
        "w_up": P(*lead, "ep", "fsdp", "tp"),
        "w_down": P(*lead, "ep", "tp", "fsdp"),   # [L?, E, F, D]
    }


def init_moe_params(key, n_layers: int, d_model: int, d_ff: int,
                    cfg: MoEConfig, dtype) -> Dict[str, Any]:
    kr, kg, ku, kd = jax.random.split(key, 4)
    L, D, F, E = n_layers, d_model, d_ff, cfg.n_experts

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    return {
        # Router in f32: small, and routing decisions are precision-
        # sensitive (standard practice).
        "router": (jax.random.normal(kr, (L, D, E), jnp.float32) * D ** -0.5),
        "w_gate": dense(kg, (L, E, D, F), D),
        "w_up": dense(ku, (L, E, D, F), D),
        "w_down": dense(kd, (L, E, F, D), F),
    }


def moe_ffn(x, lp, cfg: MoEConfig):
    """One MoE FFN block. ``x``: [B, T, D] (cfg.dtype); ``lp``: this
    layer's param dict (no leading L). Returns (y [B, T, D], aux_loss
    scalar f32).

    Dispatch math follows GShard: one-hot ``dispatch [B,T,E,C]``
    scatters tokens into per-expert capacity slots, the ``ebcd``
    einsums move tokens to the ``ep``-sharded expert dim (GSPMD →
    all-to-all over ICI), experts run SwiGLU batched over their local
    shard, and ``combine`` (dispatch × gate prob) returns weighted
    outputs. Tokens over capacity are dropped (their residual path
    passes through unchanged — standard Switch behavior).
    """
    B, T, D = x.shape
    E, K = cfg.n_experts, cfg.top_k
    C = capacity(cfg, T)

    logits = jnp.einsum("btd,de->bte", x.astype(jnp.float32), lp["router"])
    probs = jax.nn.softmax(logits, axis=-1)            # [B, T, E]

    # Top-k expert choice per token.
    gate_vals, gate_idx = jax.lax.top_k(probs, K)      # [B, T, K]
    # Renormalize the chosen gates (GShard: combine weights sum to 1).
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)

    # Capacity positions: for the k-th choice, a token's slot in expert
    # e is the number of earlier (token-major, choice-major) claims on
    # e. Flatten choices so priorities are (t, k) ordered.
    sel = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)   # [B, T, K, E]
    # (t, k) priority: token t's k-th choice claims a slot before any
    # claim of token t+1.
    sel_flat = sel.reshape(B, T * K, E)
    pos = jnp.cumsum(sel_flat, axis=1) - sel_flat      # claims before mine
    pos = pos.reshape(B, T, K, E)
    within = (pos < C) * sel                           # keep under-capacity
    slot = pos.astype(jnp.int32)

    # dispatch [B, T, E, C]: 1 where token (b,t) occupies slot c of e.
    slot_oh = jax.nn.one_hot(slot, C, dtype=jnp.float32)   # [B, T, K, E, C]
    dispatch = jnp.einsum("btke,btkec->btec", within, slot_oh)
    combine = jnp.einsum("btk,btke,btkec->btec",
                         gate_vals, within, slot_oh)

    # Load-balancing aux loss (Switch eq. 4): E * sum_e f_e * p_e with
    # f = fraction of tokens whose TOP-1 lands on e, p = mean prob.
    top1 = jax.nn.one_hot(gate_idx[..., 0], E, dtype=jnp.float32)
    aux = cfg.aux_loss_coef * E * jnp.sum(
        top1.mean((0, 1)) * probs.mean((0, 1)))

    # To experts (ep all-to-all by GSPMD), run SwiGLU, and back.
    xin = jnp.einsum("btec,btd->ebcd", dispatch.astype(x.dtype), x)
    g = jax.nn.silu(jnp.einsum("ebcd,edf->ebcf", xin,
                               lp["w_gate"]).astype(jnp.float32))
    u = jnp.einsum("ebcd,edf->ebcf", xin, lp["w_up"]).astype(jnp.float32)
    h = (g * u).astype(x.dtype)
    xout = jnp.einsum("ebcf,efd->ebcd", h, lp["w_down"])
    y = jnp.einsum("btec,ebcd->btd", combine.astype(x.dtype), xout)
    return y.astype(x.dtype), aux
