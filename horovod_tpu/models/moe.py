"""Mixture-of-Experts FFN with expert parallelism over the ``ep`` mesh
axis.

The reference has no MoE (its only relevant primitive is alltoall,
``horovod/common/operations.cc:1131`` — SURVEY.md §2.6 explicitly maps
MoE expert dispatch onto it). The TPU-native design is the GShard
dense-dispatch formulation: routing builds one-hot dispatch/combine
tensors and the expert dimension is *sharded over* ``ep``, so GSPMD
lowers the two dispatch einsums to ICI all-to-alls — no hand-written
collectives, fully fused by XLA, and differentiable end to end.

Shapes (per layer): tokens ``[B, T, D]``, experts ``E``, per-group
capacity ``C = ceil(k · T · capacity_factor / E)`` with groups = batch
rows. Top-k (default 2) gating with the standard load-balancing
auxiliary loss (Switch/GShard form).
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

#: Dispatch-plane values for the HOROVOD_MOE_DISPATCH knob /
#: ``TransformerConfig.moe_dispatch`` (docs/perf_tuning.md).
MOE_DISPATCH_MODES = ("gspmd", "island")


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01


def capacity(cfg: MoEConfig, seq_len: int) -> int:
    return max(1, math.ceil(cfg.top_k * seq_len * cfg.capacity_factor
                            / cfg.n_experts))


def moe_param_specs(n_layers_leading: bool = True) -> Dict[str, Any]:
    """PartitionSpecs for one MoE FFN block (leading ``L`` dim when
    stacked for the layer scan): experts over ``ep``, matrix dims over
    ``fsdp``/``tp`` like the dense FFN."""
    lead = (None,) if n_layers_leading else ()
    return {
        "router": P(*lead, None, None),           # [L?, D, E] replicated
        "w_gate": P(*lead, "ep", "fsdp", "tp"),   # [L?, E, D, F]
        "w_up": P(*lead, "ep", "fsdp", "tp"),
        "w_down": P(*lead, "ep", "tp", "fsdp"),   # [L?, E, F, D]
    }


def init_moe_params(key, n_layers: int, d_model: int, d_ff: int,
                    cfg: MoEConfig, dtype) -> Dict[str, Any]:
    kr, kg, ku, kd = jax.random.split(key, 4)
    L, D, F, E = n_layers, d_model, d_ff, cfg.n_experts

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    return {
        # Router in f32: small, and routing decisions are precision-
        # sensitive (standard practice).
        "router": (jax.random.normal(kr, (L, D, E), jnp.float32) * D ** -0.5),
        "w_gate": dense(kg, (L, E, D, F), D),
        "w_up": dense(ku, (L, E, D, F), D),
        "w_down": dense(kd, (L, E, F, D), F),
    }


def _route(x, router, cfg: MoEConfig, C: int):
    """GShard routing on ``x`` [B, T, D] (any batch slice): top-k
    gating, (t, k)-ordered capacity assignment, one-hot dispatch /
    combine tensors. Per-token math only — no cross-batch-row coupling
    (the capacity cumsum runs within each row), so routing a batch
    SHARD equals the global routing restricted to those rows. The
    island leans on exactly this property.

    Returns ``(dispatch [B,T,E,C], combine [B,T,E,C], probs [B,T,E],
    top1 [B,T,E], sel [B,T,K,E], within [B,T,K,E])``.
    """
    B, T, _D = x.shape
    E, K = cfg.n_experts, cfg.top_k

    logits = jnp.einsum("btd,de->bte", x.astype(jnp.float32), router)
    probs = jax.nn.softmax(logits, axis=-1)            # [B, T, E]

    # Top-k expert choice per token.
    gate_vals, gate_idx = jax.lax.top_k(probs, K)      # [B, T, K]
    # Renormalize the chosen gates (GShard: combine weights sum to 1).
    gate_vals = gate_vals / jnp.maximum(
        gate_vals.sum(-1, keepdims=True), 1e-9)

    # Capacity positions: for the k-th choice, a token's slot in expert
    # e is the number of earlier (token-major, choice-major) claims on
    # e. Flatten choices so priorities are (t, k) ordered.
    sel = jax.nn.one_hot(gate_idx, E, dtype=jnp.float32)   # [B, T, K, E]
    # (t, k) priority: token t's k-th choice claims a slot before any
    # claim of token t+1.
    sel_flat = sel.reshape(B, T * K, E)
    pos = jnp.cumsum(sel_flat, axis=1) - sel_flat      # claims before mine
    pos = pos.reshape(B, T, K, E)
    within = (pos < C) * sel                           # keep under-capacity
    slot = pos.astype(jnp.int32)

    # dispatch [B, T, E, C]: 1 where token (b,t) occupies slot c of e.
    slot_oh = jax.nn.one_hot(slot, C, dtype=jnp.float32)   # [B, T, K, E, C]
    dispatch = jnp.einsum("btke,btkec->btec", within, slot_oh)
    combine = jnp.einsum("btk,btke,btkec->btec",
                         gate_vals, within, slot_oh)

    top1 = jax.nn.one_hot(gate_idx[..., 0], E, dtype=jnp.float32)
    return dispatch, combine, probs, top1, sel, within


def _expert_ffn(xin, lp, dtype):
    """SwiGLU over per-expert token slabs ``xin`` [E', b, C, D] with
    expert weights ``lp`` [E', D, F] — shared verbatim by the GSPMD
    path (E' = E, b = B) and the island (E' = E/ep, b = ep·B/ep), so
    the per-element contraction math is identical in both."""
    g = jax.nn.silu(jnp.einsum("ebcd,edf->ebcf", xin,
                               lp["w_gate"]).astype(jnp.float32))
    u = jnp.einsum("ebcd,edf->ebcf", xin, lp["w_up"]).astype(jnp.float32)
    h = (g * u).astype(dtype)
    return jnp.einsum("ebcf,efd->ebcd", h, lp["w_down"])


def moe_ffn(x, lp, cfg: MoEConfig):
    """One MoE FFN block. ``x``: [B, T, D] (cfg.dtype); ``lp``: this
    layer's param dict (no leading L). Returns (y [B, T, D], aux_loss
    scalar f32).

    Dispatch math follows GShard: one-hot ``dispatch [B,T,E,C]``
    scatters tokens into per-expert capacity slots, the ``ebcd``
    einsums move tokens to the ``ep``-sharded expert dim (GSPMD →
    all-to-all over ICI), experts run SwiGLU batched over their local
    shard, and ``combine`` (dispatch × gate prob) returns weighted
    outputs. Tokens over capacity are dropped (their residual path
    passes through unchanged — standard Switch behavior).
    """
    E = cfg.n_experts
    C = capacity(cfg, x.shape[1])
    dispatch, combine, probs, top1, _sel, _within = _route(
        x, lp["router"], cfg, C)

    # Load-balancing aux loss (Switch eq. 4): E * sum_e f_e * p_e with
    # f = fraction of tokens whose TOP-1 lands on e, p = mean prob.
    aux = cfg.aux_loss_coef * E * jnp.sum(
        top1.mean((0, 1)) * probs.mean((0, 1)))

    # To experts (ep all-to-all by GSPMD), run SwiGLU, and back.
    xin = jnp.einsum("btec,btd->ebcd", dispatch.astype(x.dtype), x)
    xout = _expert_ffn(xin, lp, x.dtype)
    y = jnp.einsum("btec,ebcd->btd", combine.astype(x.dtype), xout)
    return y.astype(x.dtype), aux


# ---------------------------------------------------------------------------
# The quantized-dispatch island (ISSUE 18)
# ---------------------------------------------------------------------------

def moe_ffn_island(x, lp, cfg: MoEConfig, mesh, *, codec: str = "int8"):
    """:func:`moe_ffn` with the dispatch/combine hops as an explicit
    ``shard_map`` island over ``ep``, both riding
    :func:`~horovod_tpu.ops.quantized.quantized_alltoall` — the EQuARX
    treatment applied to the one collective that dominates sparse-model
    step time (the reference's alltoall, ``operations.cc:1131``).

    Token rows are batch-sharded over ``ep`` inside the island; each
    shard routes its rows locally (identical to the global routing —
    the capacity cumsum is per batch row, see :func:`_route`), packs
    per-expert token slabs, and exchanges them with the expert owners
    over the quantized alltoall: blockwise int8 (+f32 scales) at
    ~1/3.94 of the f32 wire bytes, bf16 at 1/2, ``"none"`` the plain
    f32 hop (same island math, lossless wire — the A/B control the
    int8 error-bound tests compare against). The expert SwiGLU and the
    combine weighting are byte-for-byte the GSPMD path's math.

    Requires ``B % ep == 0`` and ``E % ep == 0``. On legacy jax the
    island must be spelled full-manual (the embed-island generation
    gate), which is legal only when every non-``ep`` mesh axis is
    size 1 — :func:`make_moe_ffn` enforces that at build time.

    Capacity overflow is handled exactly like the GSPMD path (dropped
    tokens ride the residual stream); :func:`moe_routing_stats` is the
    telemetry face of the same routing math.
    """
    from horovod_tpu.common import jax_compat
    from horovod_tpu.common.jax_compat import shard_map
    from horovod_tpu.ops.quantized import quantized_alltoall

    ep = mesh.shape.get("ep", 1) if mesh is not None else 1
    if ep <= 1:
        return moe_ffn(x, lp, cfg)       # no exchange to quantize
    E = cfg.n_experts
    B, T, D = x.shape
    C = capacity(cfg, T)
    if E % ep:
        raise ValueError(
            f"moe_ffn_island: n_experts={E} must divide by the ep axis "
            f"size {ep} (each shard owns E/ep experts)")
    if B % ep:
        raise ValueError(
            f"moe_ffn_island: batch {B} must divide by the ep axis "
            f"size {ep} (token rows are batch-sharded over ep)")
    e_loc = E // ep

    def island(xl, router, wg, wu, wd):
        b_loc = xl.shape[0]
        dispatch, combine, probs, top1, _sel, _within = _route(
            xl, router, cfg, C)

        # Aux loss from the GLOBAL f/p vectors (pmean of equal-sized
        # shard means == the global mean), so the island's aux equals
        # the GSPMD path's — NOT a pmean of per-shard aux values,
        # which would average the nonlinear f·p product instead.
        f = lax.pmean(top1.mean((0, 1)), "ep")
        pbar = lax.pmean(probs.mean((0, 1)), "ep")
        aux = cfg.aux_loss_coef * E * jnp.sum(f * pbar)

        # Pack per-expert slabs for ALL E experts from local rows,
        # grouped by owner shard, and trade them: after the alltoall,
        # axis 0 indexes the SOURCE shard and the local expert slabs
        # cover this shard's E/ep experts for every token row.
        xin = jnp.einsum("btec,btd->ebcd", dispatch.astype(xl.dtype), xl)
        xin = xin.reshape(ep, e_loc, b_loc, C, D)
        r = quantized_alltoall(xin, "ep", codec=codec)
        r = jnp.moveaxis(r, 0, 1).reshape(e_loc, ep * b_loc, C, D)

        xout = _expert_ffn(r, {"w_gate": wg, "w_up": wu, "w_down": wd},
                           xl.dtype)

        # Quantized combine hop back to the token owners (axis 0 now
        # indexes the expert-OWNER shard), then the weighted combine.
        back = jnp.moveaxis(xout.reshape(e_loc, ep, b_loc, C, D), 0, 1)
        back = quantized_alltoall(back, "ep", codec=codec)
        xfull = back.reshape(E, b_loc, C, D)
        y = jnp.einsum("btec,ebcd->btd", combine.astype(xl.dtype), xfull)
        return y.astype(xl.dtype), aux

    # Modern jax: partial-manual over ep only (dp/fsdp/tp ride
    # auto/GSPMD). Legacy jax cannot lower partial-manual (the
    # embed-island gate); full-manual is correct because make_moe_ffn
    # guarantees every non-ep axis is size 1 there.
    axis_names = {"ep"} if jax_compat.HAS_NEW_SHARD_MAP else None
    # check_vma=False: the VMA checker cannot see that the pmean'd aux
    # is replicated over ep (same limitation as the embed island).
    return shard_map(
        island, mesh=mesh,
        in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep")),
        out_specs=(P("ep"), P()),
        axis_names=axis_names, check_vma=False)(
        x, lp["router"], lp["w_gate"], lp["w_up"], lp["w_down"])


def resolve_moe_knobs(dispatch: Optional[str] = None,
                      codec: Optional[str] = None):
    """Resolve the MoE dispatch-plane knobs: explicit config values win,
    ``None`` falls back to the env knobs (docs/perf_tuning.md) —
    ``HOROVOD_MOE_DISPATCH`` (default ``gspmd``) and
    ``HOROVOD_MOE_COMPRESSION`` (default ``int8``, the codec the
    island exists for). Returns ``(dispatch, codec)`` validated."""
    from horovod_tpu.ops.quantized import CODECS

    d = dispatch or os.environ.get("HOROVOD_MOE_DISPATCH", "gspmd")
    c = codec or os.environ.get("HOROVOD_MOE_COMPRESSION", "int8")
    if d not in MOE_DISPATCH_MODES:
        raise ValueError(
            f"unknown MoE dispatch mode {d!r}; one of {MOE_DISPATCH_MODES}")
    if c not in CODECS:
        raise ValueError(f"unknown MoE codec {c!r}; one of {CODECS}")
    return d, c


def make_moe_ffn(cfg: MoEConfig, mesh, *, dispatch: Optional[str] = None,
                 codec: Optional[str] = None):
    """Single construction point for the transformer's MoE FFN call:
    returns ``fn(x, lp) -> (y, aux)``.

    Routing discipline (the PR 9 ``compression=none`` contract):
    ``dispatch="gspmd"``, ``codec="none"``, a meshless build, or
    ``ep == 1`` all take the EXACT pre-existing GSPMD einsum path —
    so "island at compression=none is bitwise-identical to GSPMD"
    holds by construction, and only a genuinely narrow wire pays the
    island's restructuring. ``dispatch="island"`` with a lossy codec
    builds :func:`moe_ffn_island`; build-time failures (legacy jax
    with a non-ep axis > 1, E not divisible by ep) raise HERE with
    the mesh in hand, not mid-trace.
    """
    from horovod_tpu.common import jax_compat

    d, c = resolve_moe_knobs(dispatch, codec)
    ep = mesh.shape.get("ep", 1) if mesh is not None else 1
    if d == "gspmd" or c == "none" or ep <= 1:
        return lambda x, lp: moe_ffn(x, lp, cfg)
    if cfg.n_experts % ep:
        raise ValueError(
            f"moe_dispatch='island': n_experts={cfg.n_experts} must "
            f"divide by ep={ep}")
    if not jax_compat.HAS_NEW_SHARD_MAP:
        bad = [(ax, sz) for ax, sz in mesh.shape.items()
               if ax != "ep" and sz > 1]
        if bad:
            raise ValueError(
                "moe_dispatch='island' on legacy jax runs the island "
                f"full-manual (the embed-island generation gate); mesh "
                f"axes {bad} must be size 1 there. Use an ep-only mesh "
                "or moe_dispatch='gspmd'.")
    return lambda x, lp: moe_ffn_island(x, lp, cfg, mesh, codec=c)


# ---------------------------------------------------------------------------
# Routing telemetry (overflow counter / dropped-token fraction)
# ---------------------------------------------------------------------------

#: Python-plane MoE metric keys, locked to docs/observability.md by the
#: tools/lint metric-sync rule (same lockstep discipline as the native
#: registry's name tables).
MOE_METRIC_KEYS = (
    "moe_dispatch_overflow_tokens_total",
    "moe_dispatch_dropped_token_frac",
    "moe_dispatch_bytes_saved_pct",
)

_moe_metrics: Dict[str, float] = {}
_moe_metrics_lock = threading.Lock()


def moe_routing_stats(x, router, cfg: MoEConfig) -> Dict[str, float]:
    """Capacity-overflow telemetry for one batch: runs the exact
    routing math of :func:`_route` (so the numbers describe what the
    dispatch actually dropped, not an estimate) and returns

    * ``moe_dispatch_overflow_tokens_total`` — (token, choice) claims
      that landed past an expert's capacity this batch;
    * ``moe_dispatch_dropped_token_frac`` — that count over the
      ``B·T·k`` total claims.

    Host-callable (no mesh needed — routing is per batch row); feed
    the result to :func:`record_moe_stats` to accumulate into the
    exported series.
    """
    C = capacity(cfg, x.shape[1])
    _d, _c, _p, _t1, sel, within = _route(x, router, cfg, C)
    claims = float(sel.sum())
    overflow = claims - float(within.sum())
    return {
        "moe_dispatch_overflow_tokens_total": overflow,
        "moe_dispatch_dropped_token_frac": (
            overflow / claims if claims else 0.0),
    }


def _render_moe_metrics() -> str:
    from horovod_tpu.metrics import NAMESPACE, render_gauges
    with _moe_metrics_lock:
        vals = dict(_moe_metrics)
    return render_gauges(NAMESPACE, vals)


def record_moe_stats(stats: Dict[str, float]) -> None:
    """Fold one batch's telemetry into the exported MoE series:
    ``*_total`` keys accumulate (counters), everything else is a
    last-value gauge. First call registers the exporter, so the rows
    ride :func:`horovod_tpu.metrics.metrics_prometheus` alongside the
    native registry (docs/observability.md)."""
    from horovod_tpu.metrics import register_exporter
    with _moe_metrics_lock:
        register = not _moe_metrics
        for k, v in stats.items():
            if k.endswith("_total"):
                _moe_metrics[k] = _moe_metrics.get(k, 0.0) + float(v)
            else:
                _moe_metrics[k] = float(v)
    if register:
        register_exporter("moe", _render_moe_metrics)


def moe_metrics() -> Dict[str, float]:
    """Current values of the recorded MoE series (empty before the
    first :func:`record_moe_stats`)."""
    with _moe_metrics_lock:
        return dict(_moe_metrics)
