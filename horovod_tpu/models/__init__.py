"""Model zoo — benchmark-grade models the framework trains natively.

The reference repo ships example models through TF/Keras/torchvision
(``examples/pytorch/pytorch_synthetic_benchmark.py`` uses
torchvision's ResNet-50; ``examples/tensorflow2/
tensorflow2_synthetic_benchmark.py`` the Keras one). A standalone TPU
framework cannot lean on torchvision, so the benchmark model families
live here, written JAX-first (bf16 matmuls on the MXU, static shapes,
scan-over-layers for compile time, explicit mesh shardings).
"""

from horovod_tpu.models.moe import (  # noqa: F401
    MoEConfig,
    init_moe_params,
    moe_ffn,
    moe_param_specs,
)
from horovod_tpu.models.resnet import ResNetConfig, resnet50, resnet101  # noqa: F401
from horovod_tpu.models.transformer import (  # noqa: F401
    TransformerConfig,
    init_params as init_transformer,
    forward as transformer_forward,
    lm_loss,
    make_train_step,
    param_specs,
)
