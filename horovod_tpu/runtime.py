"""Eager runtime: Python orchestration over the native coordination core.

The split mirrors the reference: the C++ core owns negotiation, fusion
planning, caching, stall detection and the host (TCP) data plane
(reference ``horovod/common/operations.cc``); this module owns

* tensor registries (keeping inputs/outputs alive while in flight),
* the output **allocator callback** (the ``OpContext::AllocateOutput``
  analog, reference ``common/common.h:196-210``) for late-sized
  allgather/alltoall outputs, and
* the **XLA executor callback** — the NCCL-ops analog: CALLBACK-mode
  responses (JAX device arrays) are executed as jitted XLA collective
  programs instead of being routed through host TCP.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from horovod_tpu.common import basics
from horovod_tpu.common.exceptions import HorovodInternalError
from horovod_tpu.common.ops_enum import ReduceOp
from horovod_tpu.common.topology import Topology, topology_from_env
from horovod_tpu.compression import wire_codec_id


def _contig(a: np.ndarray) -> np.ndarray:
    """C-contiguous view/copy that PRESERVES 0-d shape
    (``np.ascontiguousarray`` silently promotes 0-d to shape (1,))."""
    out = np.ascontiguousarray(a)
    if out.shape != np.shape(a):
        out = out.reshape(np.shape(a))
    return out


class _InFlight:
    """State for one in-flight collective (registry entry)."""

    __slots__ = ("name", "op", "input_np", "input_dev", "output", "orig_kind",
                 "orig_dtype", "reduce_op", "prescale", "postscale", "splits",
                 "recvsplits", "root_rank")

    def __init__(self):
        self.name = None
        self.op = None
        self.input_np = None      # host buffer (kept alive for native core)
        self.input_dev = None     # jax array for CALLBACK mode
        self.output = None
        self.orig_kind = "np"     # np | jax | torch
        self.orig_dtype = None
        self.reduce_op = ReduceOp.AVERAGE
        self.prescale = 1.0
        self.postscale = 1.0
        self.splits = None
        self.recvsplits = None
        self.root_rank = 0


class Handle:
    """Async collective handle (reference ``horovod/torch/mpi_ops.py``
    handle model + ``handle_manager.h``)."""

    __slots__ = ("native", "runtime")

    def __init__(self, native: int, runtime: "Runtime"):
        self.native = native
        self.runtime = runtime


class Runtime:
    def __init__(self):
        self.lib = None
        self.topology: Optional[Topology] = None
        self._lock = threading.RLock()
        self._inflight: Dict[int, _InFlight] = {}   # native handle -> state
        self._name_to_handle: Dict[str, int] = {}
        self._name_counters: Dict[str, int] = {}
        self._exec_cb = None   # keep callbacks alive for the C core
        self._alloc_cb = None
        self._init_epoch = 0   # keys rendezvous rediscovery on re-init
        self._jax_dist_up = False
        self._exec_worker = None  # elastic device-program worker (watchdog)
        self._exec_q = None
        # Coordinator-address KV key coordinates: (elastic epoch, count
        # of world formations within that epoch). Survivors and freshly
        # respawned workers must derive the SAME key, so it cannot be
        # keyed on the per-process _init_epoch — after a respawn the
        # newcomer is at init 0 while survivors are at init k. The
        # elastic epoch is driver-published and identical everywhere;
        # the per-epoch sequence covers same-epoch re-inits (transient
        # global errors roll no epoch but every process re-inits once).
        self._xla_world_seq = 0
        self._xla_world_epoch_tag: Optional[str] = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def init(self, topology: Optional[Topology] = None) -> None:
        if self.initialized():
            return
        if (topology is None and os.environ.get("HOROVOD_ELASTIC_ID")
                and os.environ.get("HOROVOD_RENDEZVOUS_ADDR")):
            # Driver-spawned elastic worker: the spawn env's epoch (and
            # its controller address) may already be stale if membership
            # churned while this interpreter came up. Rendezvous at the
            # newest driver epoch with in-process retries instead of
            # dying a nonzero death the driver would count as a host
            # flap (elastic.initial_init re-enters here with an
            # explicit topology).
            from horovod_tpu import elastic
            elastic.initial_init(self)
            return
        self.lib = basics.get_lib()
        topo = topology or topology_from_env()
        discovered = False
        if (topo.size > 1 and "HOROVOD_CONTROLLER_ADDR" not in os.environ
                and os.environ.get("HOROVOD_RENDEZVOUS_ADDR")):
            # horovodrun job: discover the controller address through
            # the launcher's KV store instead of a pre-agreed port. The
            # init epoch keys the lookup so a shutdown + re-init gets a
            # fresh port, not the stale published one.
            from horovod_tpu.runner.rendezvous import discover_controller_addr
            timeout = float(os.environ.get("HOROVOD_START_TIMEOUT", "120"))
            os.environ["HOROVOD_CONTROLLER_ADDR"] = discover_controller_addr(
                topo.rank, timeout, epoch=self._init_epoch)
            discovered = True
        if (os.environ.get("HOROVOD_TIMELINE")
                and os.environ.get("HOROVOD_TIMELINE_RANK_SUFFIX") == "1"):
            # Uniform-env launchers (--mpi) cannot suffix the timeline
            # path per slot the way _slot_env does; apply it here, once
            # (the flag is cleared so an elastic re-init in the same
            # process does not re-append).
            os.environ["HOROVOD_TIMELINE"] += f".{topo.rank}"
            os.environ["HOROVOD_TIMELINE_RANK_SUFFIX"] = "0"
        if os.environ.get("HOROVOD_XLA_EXEC") == "1":
            if topo.size > 1:
                self._init_jax_distributed(topo)
            elif self._jax_dist_up:
                # The world shrank to one process (elastic scale-down):
                # the old multi-process XLA runtime is stale; tear it
                # down so jax sees only local devices again.
                self._teardown_jax_distributed()
        self._exec_cb = basics.EXEC_CB_TYPE(self._on_exec)
        self._alloc_cb = basics.ALLOC_CB_TYPE(self._on_alloc)
        self.lib.hvd_set_exec_callback(self._exec_cb)
        self.lib.hvd_set_alloc_callback(self._alloc_cb)
        rc = self.lib.hvd_init(topo.rank, topo.size, topo.local_rank,
                               topo.local_size, topo.cross_rank,
                               topo.cross_size)
        if discovered:
            # The native core has read the env var; don't leak a stale
            # address into re-inits or worker subprocesses.
            os.environ.pop("HOROVOD_CONTROLLER_ADDR", None)
        self._init_epoch += 1
        if rc != 0:
            raise HorovodInternalError("native core initialization failed")
        self.topology = topo

    def _init_jax_distributed(self, topo: Topology) -> None:
        """Bring up the process-spanning XLA runtime (``--xla-exec``):
        ``jax.distributed`` + gloo CPU collectives, so eager CALLBACK
        responses execute as cross-process XLA programs instead of
        staging through the host TCP plane. Must run before the local
        jax backend initializes."""
        import jax

        if self._jax_dist_up:
            # Elastic re-init: membership changed (or a peer died), so
            # the live world is stale — its size may be wrong and its
            # peer connections may be broken. Re-form it at the new
            # membership, the way the reference re-creates its comm
            # context on every rendezvous (``gloo/gloo_context.cc:
            # 154-200``), instead of silently keeping the old one.
            self._teardown_jax_distributed()
        elif self._init_epoch > 0:
            # Re-init after a size-1 interlude (shrink to one, then
            # grow): the interlude's jax calls re-created the LOCAL
            # backend, and ``jax.distributed.initialize`` refuses to
            # run after any backend use — flush it exactly like a full
            # teardown would (a no-op if nothing was initialized).
            import jax.extend.backend as jax_backend
            jax.clear_caches()
            jax_backend.clear_backends()
            from horovod_tpu.ops import xla_exec
            xla_exec.invalidate_world()
        coord = os.environ.get("HOROVOD_XLA_COORD_ADDR")
        if coord and os.environ.get("HOROVOD_ELASTIC_ID"):
            # A static coordinator address cannot follow rank 0 across
            # membership changes (the configured host may be the very
            # one that died); elastic jobs always rendezvous the
            # epoch's coordinator through the launcher KV.
            coord = None
        if not coord:
            if not os.environ.get("HOROVOD_RENDEZVOUS_ADDR"):
                raise HorovodInternalError(
                    "HOROVOD_XLA_EXEC=1 needs HOROVOD_XLA_COORD_ADDR or a "
                    "launcher rendezvous (HOROVOD_RENDEZVOUS_ADDR)")
            from horovod_tpu.runner.http_kv import kv_put, kv_wait
            from horovod_tpu.runner.rendezvous import free_port
            rdv = os.environ["HOROVOD_RENDEZVOUS_ADDR"]
            timeout = float(os.environ.get("HOROVOD_START_TIMEOUT", "120"))
            tag = os.environ.get("HOROVOD_ELASTIC_EPOCH", "")
            if tag != self._xla_world_epoch_tag:
                self._xla_world_epoch_tag = tag
                self._xla_world_seq = 0
            key = f"xla_coord_addr.{tag or 0}.{self._xla_world_seq}"
            if topo.rank == 0:
                host = os.environ.get("HOROVOD_CONTROLLER_HOST")
                if not host:
                    # Uniform-env launchers (--mpi) cannot know which
                    # node gets rank 0; advertise our own outbound IP.
                    from horovod_tpu.runner.hosts import local_ip
                    host = local_ip()
                coord = f"{host}:{free_port()}"
                kv_put(rdv, "global", key, coord.encode())
            else:
                coord = kv_wait(rdv, "global", key, timeout).decode()
        # Probing the backend here would initialize it — too early.
        # Decide CPU-ness from the environment alone.
        if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:
                pass
        start_timeout = float(os.environ.get("HOROVOD_START_TIMEOUT", "120"))
        # Peers come up within the launcher's start timeout or not at
        # all; jax's 300 s default would stall failure detection.
        kwargs = {"initialization_timeout": max(10, int(start_timeout))}
        if os.environ.get("HOROVOD_ELASTIC_ID"):
            # Elastic job: peers can die at any time. Recoverable tasks
            # skip the coordination service's shutdown barrier — without
            # this, a survivor's teardown blocks on the dead peer for
            # the full heartbeat timeout and then LOG(FATAL)s the
            # process (xla client.h). Short timeouts bound how long the
            # re-formation can lag behind the host-plane failure.
            jax.config.update("jax_enable_recoverability", True)
            kwargs.update(heartbeat_timeout_seconds=10,
                          shutdown_timeout_seconds=10)
        try:
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=topo.size,
                                       process_id=topo.rank, **kwargs)
        except Exception as e:
            # A half-formed runtime (service up, a peer never joined)
            # must not poison the next attempt with jax's
            # "should only be called once" guard.
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            self._force_reset_jax_dist_state()
            raise HorovodInternalError(
                f"jax.distributed initialization failed: {e}") from e
        finally:
            # Advance on ATTEMPT, not success: formation outcomes can
            # diverge (rank j times out while others connect), and a
            # success-only increment would leave rank j deriving the
            # previous key — and reading its stale coordinator address
            # — on the next same-epoch attempt.
            self._xla_world_seq += 1
        self._jax_dist_up = True

    def _teardown_jax_distributed(self) -> None:
        """Tear down the process-spanning XLA runtime so a later init
        can form a fresh one. Backends must be cleared too — they hold
        the old distributed client — and with them every cached mesh
        and jitted program that baked in the old device set. Live jax
        arrays stay readable (their buffers outlive the backend cache),
        so committed elastic state survives the re-formation."""
        import jax

        try:
            jax.distributed.shutdown()
        except Exception:
            # A dead peer (the very thing that triggered the reset) can
            # break the coordination service's teardown handshake; the
            # client is discarded either way.
            self._force_reset_jax_dist_state()
        jax.clear_caches()
        import jax.extend.backend as jax_backend
        jax_backend.clear_backends()
        from horovod_tpu.ops import xla_exec
        xla_exec.invalidate_world()
        self._jax_dist_up = False

    @staticmethod
    def _force_reset_jax_dist_state() -> None:
        """Failure-path fallback when the public shutdown cannot run to
        completion: drop the distributed client state directly so a
        later ``initialize`` doesn't refuse with "should only be called
        once". Private-API touch, used only after a failed shutdown or
        a failed initialize."""
        try:
            from jax._src import distributed as jax_dist
            st = jax_dist.global_state
            st.client = None
            st.service = None
            st.preemption_sync_manager = None
            st.coordinator_address = None
            st.process_id = 0
            st.num_processes = 1
        except Exception:
            pass

    def shutdown(self) -> None:
        if self.lib is not None and self.initialized():
            self.lib.hvd_shutdown()
        if self._exec_q is not None:
            self._exec_q.put(None)  # end the idle watchdog worker
            self._exec_worker = None
            self._exec_q = None
        with self._lock:
            self._inflight.clear()
            self._name_to_handle.clear()
            self._name_counters.clear()

    def initialized(self) -> bool:
        return self.lib is not None and bool(self.lib.hvd_initialized())

    def rank(self) -> int:
        self._check_init()
        return self.lib.hvd_rank()

    def size(self) -> int:
        self._check_init()
        return self.lib.hvd_size()

    def local_rank(self) -> int:
        self._check_init()
        return self.lib.hvd_local_rank()

    def local_size(self) -> int:
        self._check_init()
        return self.lib.hvd_local_size()

    def cross_rank(self) -> int:
        self._check_init()
        return self.lib.hvd_cross_rank()

    def cross_size(self) -> int:
        self._check_init()
        return self.lib.hvd_cross_size()

    def reduce_threads(self) -> int:
        """Worker threads the host data plane currently spreads its
        reductions and pack/unpack copies over (``docs/perf_tuning.md``).
        Reflects the coordinator-synced ``HOROVOD_REDUCE_THREADS`` value
        and any autotuned retarget."""
        self._check_init()
        return int(self.lib.hvd_reduce_threads())

    def set_reduce_threads(self, n: int) -> None:
        """Retarget the host-reduction thread budget of THIS process
        (clamped to [1, 64]). Results are bitwise identical at any
        setting, so a per-rank override is always safe — unlike the
        protocol knobs, no cross-rank agreement is needed."""
        self._check_init()
        self.lib.hvd_set_reduce_threads(int(n))

    def _check_init(self) -> None:
        if not self.initialized():
            raise RuntimeError(
                "horovod_tpu has not been initialized; call hvd.init() first")

    # ------------------------------------------------------------------
    # enqueue / synchronize
    # ------------------------------------------------------------------

    def auto_name(self, prefix: str, explicit: Optional[str]) -> str:
        if explicit is not None:
            return explicit
        with self._lock:
            i = self._name_counters.get(prefix, 0)
            self._name_counters[prefix] = i + 1
        return f"{prefix}.noname.{i}"

    @staticmethod
    def _classify(tensor):
        """Returns (kind, np_view_or_none, jax_array_or_none)."""
        mod = type(tensor).__module__
        if isinstance(tensor, np.ndarray):
            return "np", tensor, None
        if mod.startswith("torch"):
            import torch
            t = tensor.detach()
            if t.device.type != "cpu":
                t = t.cpu()
            t = t.contiguous()
            if t.dtype == torch.bfloat16:
                # torch refuses bf16->numpy; stage through a uint16 view
                # and rewrap with ml_dtypes so the native core sees the
                # real dtype.
                import ml_dtypes
                return "torch", t.view(torch.uint16).numpy().view(
                    ml_dtypes.bfloat16), None
            return "torch", t.numpy(), None
        if mod.startswith("jax") or hasattr(tensor, "addressable_shards"):
            return "jax", None, tensor
        # Anything array-like (lists, scalars) becomes numpy.
        return "np", _contig(np.asarray(tensor)), None

    def enqueue(self, op: int, tensor, name: str, *,
                reduce_op: ReduceOp = ReduceOp.AVERAGE,
                root_rank: int = 0,
                prescale_factor: float = 1.0,
                postscale_factor: float = 1.0,
                splits=None,
                group_key: int = -1,
                group_size: int = 0,
                compression=None,
                algorithm=None) -> Handle:
        self._check_init()
        # Per-op wire codec for the host TCP data plane (-1 = follow
        # HOROVOD_WIRE_COMPRESSION). CALLBACK (XLA) responses ignore it
        # — device collectives ride ICI at their own dtype.
        wire_codec = wire_codec_id(compression)
        # Per-op allreduce algorithm (0 = follow the coordinator's
        # selection table / HOROVOD_COLLECTIVE_ALGO); resolved into
        # each response like the wire codec, so mixed per-rank settings
        # are a coordinator error, never a desynced exchange.
        collective_algo = basics.collective_algo_id(algorithm)
        kind, np_in, dev_in = self._classify(tensor)

        st = _InFlight()
        st.name = name
        st.op = op
        st.orig_kind = kind
        st.reduce_op = reduce_op
        st.prescale = prescale_factor
        st.postscale = postscale_factor
        st.root_rank = root_rank

        if kind == "jax" and self.size() > 1 and not _jax_distributed_active():
            # No process-spanning mesh available: stage through the host
            # data plane (the reference's CPU-fallback, gloo_operations.cc).
            # Loud, once — the XLA data plane is opt-in via --xla-exec.
            global _warned_host_staging
            if not _warned_host_staging:
                _warned_host_staging = True
                import warnings
                warnings.warn(
                    "horovod_tpu: jax tensors are staging through the host "
                    "TCP data plane because jax.distributed is not "
                    "initialized; launch with horovodrun --xla-exec (or set "
                    "HOROVOD_XLA_EXEC=1) for the XLA data plane",
                    RuntimeWarning, stacklevel=3)
            kind = "np"
            np_in = np.asarray(dev_in)
            st.orig_kind = "jax"

        if kind == "jax":
            # Device path: the native core negotiates; execution happens
            # in the XLA executor callback.
            exec_mode = basics.EXEC_CALLBACK
            st.input_dev = dev_in
            shape = list(dev_in.shape)
            dt = basics.dtype_id(dev_in.dtype)
            data_ptr = None
            out_ptr = None
        else:
            exec_mode = basics.EXEC_HOST
            np_in = _contig(np_in)
            st.input_np = np_in
            st.orig_dtype = np_in.dtype
            shape = list(np_in.shape)
            dt = basics.dtype_id(np_in.dtype)
            data_ptr = np_in.ctypes.data
            if op in (basics.OP_ALLREDUCE, basics.OP_BROADCAST):
                st.output = np.empty_like(np_in)
                out_ptr = st.output.ctypes.data
            else:
                out_ptr = None  # allocated by callback once sizes known

        shape_arr = (ctypes.c_int64 * len(shape))(*shape)
        if splits is not None:
            splits = list(int(s) for s in splits)
            st.splits = splits
            splits_arr = (ctypes.c_int64 * len(splits))(*splits)
            nsplits = len(splits)
        else:
            splits_arr = None
            nsplits = 0

        with self._lock:
            handle = self.lib.hvd_enqueue(
                op, name.encode(), dt, shape_arr, len(shape), data_ptr,
                out_ptr, root_rank, int(reduce_op), prescale_factor,
                postscale_factor, splits_arr, nsplits, exec_mode,
                group_key, group_size, wire_codec, collective_algo)
            if handle < 0:
                err = self.lib.hvd_last_enqueue_error().decode()
                raise HorovodInternalError(err)
            self._inflight[handle] = st
            self._name_to_handle[name] = handle
        return Handle(handle, self)

    def poll(self, handle: Handle) -> bool:
        return bool(self.lib.hvd_poll(handle.native))

    def synchronize(self, handle: Handle):
        err_buf = ctypes.create_string_buffer(1024)
        rc = self.lib.hvd_wait(handle.native, -1, err_buf, len(err_buf))
        with self._lock:
            st = self._inflight.pop(handle.native, None)
            if st is not None and self._name_to_handle.get(st.name) == handle.native:
                self._name_to_handle.pop(st.name, None)
        if rc != 0:
            self.lib.hvd_release_handle(handle.native)
            raise HorovodInternalError(
                err_buf.value.decode() or f"collective failed (rc={rc})")
        if st is None:
            self.lib.hvd_release_handle(handle.native)
            raise HorovodInternalError("unknown handle")
        # Alltoall recv splits.
        if st.op == basics.OP_ALLTOALL:
            n = self.lib.hvd_get_recvsplits(handle.native, None, 0)
            if n > 0:
                buf = (ctypes.c_int64 * n)()
                self.lib.hvd_get_recvsplits(handle.native, buf, n)
                st.recvsplits = list(buf)
        self.lib.hvd_release_handle(handle.native)

        out = st.output
        if st.orig_kind == "jax":
            import jax.numpy as jnp
            if out is None:
                out = st.input_dev
            elif not hasattr(out, "devices"):
                out = jnp.asarray(out)
            return out, st
        if st.orig_kind == "torch":
            import torch
            out = _contig(out)
            if out.dtype.name == "bfloat16":
                return torch.from_numpy(out.view(np.uint16)).view(
                    torch.bfloat16), st
            return torch.from_numpy(out), st
        return out, st

    # ------------------------------------------------------------------
    # native-core callbacks (run on the background thread)
    # ------------------------------------------------------------------

    def _on_alloc(self, handle: int, shape_ptr, ndim: int) -> int:
        try:
            shape = tuple(shape_ptr[i] for i in range(ndim))
            with self._lock:
                st = self._inflight.get(handle)
                if st is None:
                    return 0
                st.output = np.empty(shape, dtype=st.orig_dtype)
                return st.output.ctypes.data
        except Exception:
            return 0

    def _on_exec(self, exec_id: int, op: int, n: int, names_ptr, dtype: int,
                 sizes_ptr, sizes_len: int, reduce_op: int,
                 contributes: int) -> None:
        try:
            names = [names_ptr[i].decode() for i in range(n)]
            sizes = [sizes_ptr[i] for i in range(sizes_len)] if sizes_len else []
            self._execute_xla(op, names, sizes, dtype, reduce_op,
                              bool(contributes))
            self.lib.hvd_exec_done(exec_id, 0, None)
        except Exception as e:  # noqa: BLE001 — must not unwind into C
            self.lib.hvd_exec_done(exec_id, 1, str(e).encode())

    def _execute_xla(self, op: int, names: List[str], sizes: List[int],
                     dtype: int, reduce_op: int, contributes: bool) -> None:
        """Execute one CALLBACK-mode response with XLA.

        Single-process: collectives over ranks degenerate to (scaled)
        identity. Multi-process pods run under ``jax.distributed`` with
        a process-spanning mesh (the launcher sets it up); every process
        executes this same program in the same order — the ordering is
        guaranteed by the controller's broadcast ResponseList.

        ``contributes`` comes from the Response's contributor set: only
        when this rank is genuinely a non-contributor (it joined) may a
        missing local handle be replaced by a zeros contribution
        (reference feeds zeros for joined ranks, ``operations.cc:260``).
        A missing handle on a contributing rank is a bug (name reuse,
        premature cleanup) and raises instead of corrupting the
        reduction with silent zeros.
        """
        from horovod_tpu.ops import xla_exec

        with self._lock:
            states = []
            for i, nm in enumerate(names):
                h = self._name_to_handle.get(nm)
                if h is not None and h in self._inflight:
                    states.append(self._inflight[h])
                elif not contributes and op == basics.OP_ALLREDUCE:
                    # Joined rank with no local tensor: sizes[i] is the
                    # tensor's element count.
                    states.append(xla_exec.zeros_state(
                        nm, op, sizes[i] if i < len(sizes) else 0, dtype,
                        reduce_op))
                else:
                    raise KeyError(
                        f"no in-flight state for tensor {nm!r} (op {op}, "
                        f"contributes={contributes}); a contributing rank "
                        "must hold a live handle for every response tensor")
        outs = self._run_device_program(op, states, sizes)
        with self._lock:
            for st, out in zip(states, outs):
                st.output = out

    def _run_device_program(self, op: int, states, sizes: List[int]):
        """Run one XLA device program, guarding elastic jobs against a
        peer dying mid-program: the CPU-collective rendezvous has no
        timeout, so a dead peer leaves the program blocked forever and
        with it the whole background thread (and the job — synchronize
        never returns, so the elastic reset never starts). Run the
        program on a helper thread and abandon the wait when the driver
        rolls the membership epoch; the reset that follows tears the
        world down, which cancels the stuck program's pending RPCs."""
        from horovod_tpu.ops import xla_exec

        if not (os.environ.get("HOROVOD_ELASTIC_ID") and self.size() > 1):
            return xla_exec.execute(op, states, sizes, self.size(),
                                    self.rank())

        box: Dict[str, Any] = {}
        done = threading.Event()

        def _run():
            try:
                box["outs"] = xla_exec.execute(op, states, sizes,
                                               self.size(), self.rank())
            except Exception as e:  # noqa: BLE001 — re-raised below
                box["err"] = e
            finally:
                done.set()

        if self._exec_worker is None:
            # Persistent DAEMON worker (not ThreadPoolExecutor, whose
            # non-daemon thread would be joined at interpreter exit —
            # a wedged program would then block process exit forever).
            import queue
            self._exec_q = queue.SimpleQueue()
            q = self._exec_q

            def _loop():
                while True:
                    fn = q.get()
                    if fn is None:
                        return
                    fn()

            self._exec_worker = threading.Thread(
                target=_loop, daemon=True, name="hvd-xla-exec")
            self._exec_worker.start()
        self._exec_q.put(_run)
        from horovod_tpu import elastic as _elastic
        start_epoch = int(os.environ.get("HOROVOD_ELASTIC_EPOCH", "0") or 0)
        while not done.wait(0.5):
            try:
                w = _elastic._watcher
                cur = (w.latest() if w is not None and not w.stale()
                       else _elastic.current_epoch())
            except Exception:
                continue
            if cur > start_epoch:
                # The roll may be a healthy scale-UP (all current peers
                # alive, program about to complete): grant a grace
                # window so growth doesn't cost a rollback to the last
                # commit. A dead-peer program never completes, so after
                # the grace the world is known doomed.
                grace = float(os.environ.get(
                    "HOROVOD_XLA_EXEC_GRACE_SECS", "5"))
                if done.wait(grace):
                    break
                # The stuck op wedges the worker thread until the
                # teardown cancels its RPCs; do not queue future
                # programs behind it (the daemon thread leaks at
                # worst, never blocking exit).
                self._exec_worker = None
                self._exec_q = None
                raise HorovodInternalError(
                    f"membership epoch rolled {start_epoch} -> {cur} while "
                    "a device collective was in flight; abandoning the "
                    "stale world's program")
        if "err" in box:
            raise box["err"]
        return box["outs"]

    # ------------------------------------------------------------------
    # misc
    # ------------------------------------------------------------------

    def join(self) -> Handle:
        self._check_init()
        h = self.lib.hvd_join()
        st = _InFlight()
        st.name, st.op = "join", basics.OP_JOIN
        with self._lock:
            self._inflight[h] = st
            self._name_to_handle[st.name] = h
        return Handle(h, self)

    def barrier(self) -> Handle:
        self._check_init()
        h = self.lib.hvd_barrier()
        st = _InFlight()
        st.name, st.op = "barrier", basics.OP_BARRIER
        with self._lock:
            self._inflight[h] = st
            self._name_to_handle[st.name] = h
        return Handle(h, self)

    def start_timeline(self, path: str) -> None:
        """Start — or RESTART onto a new path — the host timeline.
        Raises when the file cannot be opened (the native call used to
        silently no-op on both failure and restart)."""
        self._check_init()
        if self.lib.hvd_start_timeline(path.encode()) != 0:
            raise HorovodInternalError(
                f"could not open timeline file {path!r}")

    def stop_timeline(self) -> None:
        self._check_init()
        self.lib.hvd_stop_timeline()


_warned_host_staging = False


def _jax_distributed_active() -> bool:
    try:
        import jax
        return jax.process_count() > 1
    except Exception:
        return False


_runtime: Optional[Runtime] = None
_runtime_lock = threading.Lock()


def get_runtime() -> Runtime:
    global _runtime
    with _runtime_lock:
        if _runtime is None:
            _runtime = Runtime()
        return _runtime
