"""Serving fleet: an admission router over N in-process engine
replicas.

One :class:`~horovod_tpu.serve.engine.ServeEngine` is a single
replica; "heavy traffic from millions of users" means a fleet. The
router is the layer above the engine — it owns fleet-level admission
and placement, and the replicas stay plain engines (every replica
invariant the engine tier pins — bitwise parity, allocator safety,
backpressure — holds unchanged underneath):

* **Cache-affinity placement.** The prefix cache only pays when a
  request lands where its prefix is warm. At submit the router hashes
  the prompt's block chain ONCE (the same
  :func:`~horovod_tpu.serve.kv_cache.hash_chain` the engine publishes
  under) and at placement walks every candidate replica's content
  index (`ServeEngine.cached_chain_len`, a non-mutating peek): the
  replica holding the longest chain prefix wins; no match (or a tie)
  falls back to least-occupancy. A burst of same-prefix requests
  placed in one step would all walk cold indexes (nobody has
  prefilled yet) and scatter — the fleet-level twin of the engine's
  same-step-burst problem, solved the same way: the router keeps a
  bounded *placed-chain* index recording where each chain entry was
  last routed, and scores candidates by the max of the live index
  walk and that routing hint, so the first request of a tenant
  CREATES the affinity its burst siblings follow. Random and
  round-robin placements exist as benchmark baselines — `bench.py`'s
  routed-vs-random comparison is the tentpole claim.
* **Prefill/decode pools with KV handoff.** With
  ``RouterConfig.n_prefill > 0`` the fleet splits: prefill replicas
  run admission + (chunked) prefill only, then the router streams each
  completed sequence's block pages to a decode replica
  (`export_prefilled` -> `inject_prefilled`). Interactive decode
  traffic never queues behind a long prompt's prefill, and because the
  pages move bitwise and decode math is position-dependent only, the
  token streams are identical to a single replica serving the same
  trace (pinned by tests/test_router.py).
* **Deadline-class load shedding.** Saturation sheds the *least
  important* work first instead of blanket-503ing whoever arrives
  last: every request carries a ``deadline_class`` (0 = protected,
  higher = shed first). When the router queue is full, an arriving
  request evicts the newest queued request of a strictly lower class
  (higher number) — that victim resolves to a structured ``"shed"``
  result carrying the reason, its class, and a retry-after estimate
  from queue depth x drain rate; if nothing queued is lower-class, the
  arrival itself is rejected with :class:`FleetSaturated` carrying the
  same fields.
* **Fleet telemetry.** Each replica's :class:`ServeMetrics` exports
  with a distinct ``instance`` label, and :class:`FleetMetrics`
  renders fleet-level aggregates (summed counters, pooled latency
  tails, fleet hit rate) under ``serve_fleet_`` — one scrape of
  ``hvd.metrics_prometheus()`` covers every replica plus the rollup.

Replica membership is elastic: :meth:`ServeRouter.add_replica` joins a
fresh engine (sharing the fleet's jitted programs — same geometry, one
compile), :meth:`ServeRouter.remove_replica` drains one (queued work
is withdrawn and requeued at the router, in-flight sequences decode to
completion — or, with ``migrate_running=True``, are exported mid-decode
and injected into peers, bitwise). No request is ever dropped or
duplicated across membership changes — the randomized property test
drives exactly that.

The fleet spans processes (ISSUE 11): pass ``workers=`` (handles from
:func:`horovod_tpu.serve.rpc.spawn_worker`) and every replica becomes
a :class:`~horovod_tpu.serve.rpc.RemoteReplica` — the same engine seam
over the RPC plane, driven by the identical placement/pool/shedding/
drain code. Liveness is the transport plus a heartbeat sweep; a dead
worker's uncollected requests requeue at the queue front and resolve
exactly once on survivors. Remote step RPCs fan out (request frames to
every busy worker first, replies applied in fleet order), so N worker
processes compute their iterations concurrently while results stay
seed-deterministic. See docs/serving.md "Cross-process fleet".

The fleet is **multi-model** (ISSUE 12): the constructor registers the
``"default"`` model group, :meth:`ServeRouter.add_model` registers
more — each group carries its own model/serve configs, params (or
worker seed), and prefill/decode split — and requests carry
``model=``. Placement scores by (model, cache affinity) with capacity
filtering inside the group; handoffs, migrating drains, and
dead-worker requeue never cross groups (a KV page is meaningless under
another model's weights, so exactly-once failover is same-model by
construction); shedding stays fleet-wide by deadline class. This makes
draft/target pairs, A/B fleets, and per-tenant models ordinary fleet
members — see docs/serving.md "Multi-model fleets".

Everything is deterministic for a fixed seed: FIFO placement order,
tie-breaks by replica id, and the only randomness (the random
placement baseline) runs off the config seed.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu.serve.engine import (
    QueueFull, RequestResult, RetireEma, ServeConfig, ServeEngine,
    validate_request,
)
from horovod_tpu.serve.kv_cache import hash_chain
from horovod_tpu.serve.metrics import MAX_SAMPLES, percentile

#: Bound on the router's placed-chain hint index (16-byte hashes ->
#: ~3 MB at the cap); beyond it the oldest routing hints fall off.
#: Stale hints are harmless — the live per-replica index walk is the
#: ground truth, the hint only pre-groups same-prefix bursts.
CHAIN_INDEX_CAP = 65536


def _codec_id(name) -> int:
    from horovod_tpu.serve.rpc import span_codec_id
    return span_codec_id(name)


def _advance_membership(reason: int, rank: int = -1) -> None:
    """Tick the process-global membership plane (docs/elastic.md): the
    serving fleet's replica churn rides the same epoch
    ``hvd.membership()`` reports for training, so one monotone number
    fences both planes. Safe from any thread — the plane's fences gate
    background-owned state internally. ``rank`` names the affected
    member when there is one (a dead replica's numeric instance): the
    native plane records it in the ``peer_death`` flight event, so a
    post-mortem flight dump says WHO died, not just that someone
    did."""
    from horovod_tpu.common import basics
    basics.get_lib().hvd_membership_advance(reason, rank)


def _record_flap(identity: str) -> None:
    """Record a replica death in the decay blacklist under its fleet
    identity (same flap model the elastic driver uses for hosts)."""
    from horovod_tpu.common import basics
    basics.get_lib().hvd_blacklist_record(
        identity.encode(), time.monotonic())


class FleetSaturated(QueueFull):
    """Router-level shed: the fleet queue is full and nothing queued
    is lower-class than the arrival. Carries ``reason`` /
    ``deadline_class`` / ``retry_after_s`` like every structured
    rejection in the serve tier."""

    def __init__(self, msg: str, *, deadline_class: int,
                 queue_depth: int, retry_after_s: Optional[float]):
        super().__init__(msg, reason="shed_low_class",
                         queue_depth=queue_depth,
                         retry_after_s=retry_after_s)
        self.deadline_class = deadline_class


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Fleet knobs (per-replica knobs live in ``ServeConfig``)."""

    n_replicas: int = 2
    # Leading replicas become a prefill-only pool, the rest decode-only
    # (KV handoff between them). 0 = unified: every replica prefills
    # AND decodes, no handoff.
    n_prefill: int = 0
    # Router-held (not yet placed) request cap; beyond it the shedding
    # policy decides who loses, by deadline class.
    max_queue: int = 256
    # "affinity" (cache-aware, the point of this module) with
    # least-occupancy fallback; "least" = occupancy only;
    # "random" / "round_robin" = benchmark baselines.
    placement: str = "affinity"
    seed: int = 0                # drives the random-placement baseline
    # -- cross-process fleet knobs (docs/serving.md) -----------------
    # Seconds between liveness heartbeats to a remote replica the step
    # loop would not otherwise talk to. 0 = every step (freshest
    # metrics cache; fine on loopback), raise it on real networks.
    heartbeat_every: float = 0.0
    # Wire codec for K/V pages on RPC handoffs: None | "bf16" | "fp16"
    # (the PR 9 cast codecs; bf16 halves migration bytes with the
    # bitwise-pinned decode). Lossy for f32 pools — streams stay
    # deterministic but are the bf16-rounded ones; leave None when the
    # cross-process fleet must be bitwise the in-process one.
    handoff_compression: Optional[str] = None
    # SO_RCVTIMEO/SO_SNDTIMEO on worker connections: a worker that
    # stops answering for this long is declared dead (requeue +
    # failover). Generous default — the first step against a fresh
    # worker pays jit compiles.
    rpc_timeout: float = 300.0
    # Direct worker<->worker page migration (docs/serving.md "Direct
    # migration"): "env" defers to HOROVOD_FLEET_DIRECT_MIGRATION
    # (auto|off), or force it per-fleet — "off" is the relayed
    # export->router->inject path byte-for-byte; "auto" dials the
    # bulk channel and falls back to relayed when the dial fails.
    direct_migration: str = "env"

    def __post_init__(self):
        if self.n_replicas < 1:
            raise ValueError(f"n_replicas {self.n_replicas} < 1")
        if not 0 <= self.n_prefill < self.n_replicas:
            raise ValueError(
                f"n_prefill {self.n_prefill} must leave at least one "
                f"decode replica out of {self.n_replicas}")
        if self.placement not in ("affinity", "least", "random",
                                  "round_robin"):
            raise ValueError(f"unknown placement {self.placement!r}")
        if self.heartbeat_every < 0:
            raise ValueError(
                f"heartbeat_every {self.heartbeat_every} < 0")
        if self.direct_migration not in ("env", "auto", "off"):
            raise ValueError(
                f"unknown direct_migration {self.direct_migration!r} "
                "(want env, auto, or off)")
        # Fail on garbage at config time, not mid-handoff.
        from horovod_tpu.serve.rpc import span_codec_id
        span_codec_id(self.handoff_compression)


#: The model id of the constructor-registered group: a single-model
#: fleet never has to spell a model id anywhere.
DEFAULT_MODEL = "default"


@dataclasses.dataclass
class _Pending:
    """Router-side copy of a request: enough to (re)place it on any
    same-model replica — this is what makes replica drain lossless
    AND model-correct (a requeued request re-places only within its
    model group)."""

    rid: int
    prompt: List[int]
    max_new: int
    deadline: Optional[float]
    deadline_class: int
    submitted_at: float
    chain: List[bytes]
    model: str = DEFAULT_MODEL
    trace: int = 0               # distributed trace id (0 = unsampled)


@dataclasses.dataclass
class _ModelGroup:
    """One registered model: its configs, params (None for all-remote
    groups), pool split, and the worker params-from-seed contract.
    Replicas of different groups are ordinary fleet members — only
    placement, handoff, drain and the last-replica guard key on the
    group."""

    model_cfg: Any
    params: Any
    serve_cfg: ServeConfig
    n_prefill: int = 0
    worker_seed: int = 0


@dataclasses.dataclass
class _Replica:
    instance: str
    role: str                    # "unified" | "prefill" | "decode"
    engine: Any                  # ServeEngine | rpc.RemoteReplica
    model: str = DEFAULT_MODEL   # the _ModelGroup this replica serves
    draining: bool = False
    remote: bool = False         # engine lives in a worker process
    migrate: bool = False        # drain moves RUNNING decodes out too
    # engine rid -> router rid, for every request placed here whose
    # result has not been collected yet.
    outstanding: Dict[int, int] = dataclasses.field(default_factory=dict)


class FleetMetrics:
    """Fleet-level rollup over the replicas' ``ServeMetrics``:
    summed counters, pooled latency tails (per-replica p99s don't
    average into a fleet p99 — the samples do), token-weighted fleet
    hit rate, and the router's own counters (placements by kind,
    sheds by class, handoffs). Registers on the shared exposition
    under ``serve_fleet_`` so one scrape covers every replica AND the
    rollup."""

    #: Same single-instance-collision fix as ``ServeMetrics``: two
    #: live fleets in one process must not emit identical unlabeled
    #: ``serve_fleet_*`` samples into one scrape.
    _fleet_ids = itertools.count()

    #: Lifetime counters a reaped replica's history folds into (its
    #: ServeMetrics object dies with it; without absorption a drain
    #: would silently shrink fleet totals and break the submitted ==
    #: finished+expired+rejected balance). Point-in-time gauges
    #: (kv_blocks_*) and rates are deliberately NOT absorbed — a dead
    #: pool holds nothing.
    ABSORBED = ("tokens_generated", "requests_submitted",
                "requests_finished", "requests_expired",
                "requests_rejected", "prefix_hit_tokens",
                "prefix_prefill_tokens", "spec_proposed_total",
                "spec_accepted_total")

    def __init__(self, router: "ServeRouter"):
        import weakref

        self._router = weakref.ref(router)
        self.fleet = str(next(self._fleet_ids))
        self.placed_affinity = 0     # placements won by a chain match
        self.placed_fallback = 0     # no match: occupancy/baseline pick
        self.shed_total = 0
        self.shed_by_class: Dict[int, int] = {}
        self.expired_total = 0
        self.handoffs = 0
        # Cross-process fleet health (docs/observability.md rows):
        self.heartbeats = 0          # liveness/metrics probes sent
        self.worker_deaths = 0       # replicas declared dead (RPC fail)
        self.requeued_total = 0      # requests requeued off dead/failed
        #                              replicas (each still resolves
        #                              exactly once)
        self.migrations = 0          # RUNNING decodes moved by a drain
        # Direct-migration plane (docs/observability.md rows; the
        # exported names are pinned in serve/migrate.py
        # MIGRATION_METRIC_KEYS — lint: migration-metric-pins):
        self.direct_migrations_total = 0   # page moves over the
        #                                    worker<->worker channel
        self.migration_bytes_total = 0     # wire bytes moved by the
        #                                    page-move plane, any path
        self.migration_link_cost_us = 0.0  # last decision's alpha-beta
        #                                    cost verdict (gauge)
        self.migration_ms: List[float] = []   # per-move wall samples
        #                                       (pooled-tail histogram)
        self._retired: Dict[str, float] = {}   # absorbed counters
        # ...and the same counters bucketed by model group, feeding
        # the per-model rollup series (label model=...).
        self._retired_models: Dict[str, Dict[str, float]] = {}
        # Absorbed latency samples (same MAX_SAMPLES cap as the live
        # series): without them the fleet p99 would silently IMPROVE
        # after draining whichever replica served the slow tenant.
        self._retired_samples: Dict[str, List[float]] = {
            "first_token_s": [], "per_token_s": []}
        from horovod_tpu.metrics import register_exporter_weak
        register_exporter_weak(f"serve_fleet_{id(self)}", self,
                               "prometheus")

    def absorb(self, metrics, model: str = "default") -> None:
        """Fold a reaped replica's final ``ServeMetrics`` into the
        rollup — lifetime counters (fleet-wide AND under its model
        group) plus its latency samples (capped) — so fleet totals and
        tails survive membership churn."""
        snap = metrics.snapshot()
        by_model = self._retired_models.setdefault(model, {})
        for key in self.ABSORBED:
            self._retired[key] = (self._retired.get(key, 0)
                                  + snap.get(key, 0))
            by_model[key] = by_model.get(key, 0) + snap.get(key, 0)
        for series, kept in self._retired_samples.items():
            room = MAX_SAMPLES - len(kept)
            if room > 0:
                kept.extend(getattr(metrics, series)[:room])

    def record_placed(self, match_len: int) -> None:
        if match_len > 0:
            self.placed_affinity += 1
        else:
            self.placed_fallback += 1

    def record_shed(self, deadline_class: int) -> None:
        self.shed_total += 1
        self.shed_by_class[deadline_class] = (
            self.shed_by_class.get(deadline_class, 0) + 1)

    def record_migration_ms(self, ms: float) -> None:
        if len(self.migration_ms) < MAX_SAMPLES:
            self.migration_ms.append(float(ms))

    def snapshot(self) -> Dict[str, float]:
        router = self._router()
        if router is None:
            return {}
        reps = router._replicas
        snaps = [r.engine.metrics.snapshot() for r in reps]
        out: Dict[str, float] = {
            "replicas": len(reps),
            "queue_depth": len(router._queue),
            "placed_affinity": self.placed_affinity,
            "placed_fallback": self.placed_fallback,
            "shed_total": self.shed_total,
            "expired_total": self.expired_total,
            "handoffs": self.handoffs,
            "heartbeats": self.heartbeats,
            "worker_deaths": self.worker_deaths,
            "requeued_total": self.requeued_total,
            "migrations": self.migrations,
            "direct_migrations_total": self.direct_migrations_total,
            "migration_bytes_total": self.migration_bytes_total,
            "migration_link_cost_us": self.migration_link_cost_us,
        }
        # Page-move wall-time tails: pooled samples like every other
        # fleet histogram (a quantile of the union, not an average of
        # per-path quantiles).
        for q in (50, 99):
            v = percentile(self.migration_ms, q)
            out[f"p{q}_migration_ms"] = (None if v is None
                                         else round(v, 3))
        for c, n in sorted(self.shed_by_class.items()):
            out[f"shed_class_{c}"] = n
        for key in self.ABSORBED + ("kv_blocks_in_use",
                                    "kv_blocks_cached"):
            out[key] = (sum(s.get(key, 0) for s in snaps)
                        + self._retired.get(key, 0))
        rates = [s["tokens_per_sec"] for s in snaps]
        out["tokens_per_sec"] = round(sum(rates), 2)
        occ = [s["batch_occupancy"] for s in snaps]
        out["batch_occupancy"] = (round(sum(occ) / len(occ), 4)
                                  if occ else 0.0)
        looked = out["prefix_hit_tokens"] + out["prefix_prefill_tokens"]
        out["prefix_cache_hit_rate"] = (
            round(out["prefix_hit_tokens"] / looked, 4)
            if looked else 0.0)
        out["spec_accept_rate"] = (
            round(out["spec_accepted_total"]
                  / out["spec_proposed_total"], 4)
            if out["spec_proposed_total"] else 0.0)
        # Pooled tails: the fleet p99 is a quantile of the union of
        # every replica's samples (live + absorbed-from-reaped), not
        # an average of replica p99s.
        for series, label in (("first_token_s", "first_token_ms"),
                              ("per_token_s", "per_token_ms")):
            pooled = [x for r in reps
                      for x in getattr(r.engine.metrics, series)]
            pooled += self._retired_samples[series]
            for q in (50, 99):
                v = percentile(pooled, q)
                out[f"p{q}_{label}"] = (None if v is None
                                        else round(v * 1e3, 3))
        return out

    def snapshot_by_model(self) -> Dict[str, Dict[str, float]]:
        """Per-model-group rollups: live replicas of each group summed
        with the group's absorbed (reaped-replica) counters, plus the
        group's queue depth and accept rate. The fleet-wide snapshot
        stays the authoritative total; these slices answer "which
        model is the traffic/accept-rate/backlog on?"."""
        router = self._router()
        if router is None:
            return {}
        out: Dict[str, Dict[str, float]] = {}
        for model in sorted(router._models):
            reps = [r for r in router._replicas if r.model == model]
            snaps = [r.engine.metrics.snapshot() for r in reps]
            retired = self._retired_models.get(model, {})
            d: Dict[str, float] = {
                "replicas": len(reps),
                "queue_depth": sum(1 for q in router._queue
                                   if q.model == model),
            }
            for key in self.ABSORBED:
                d[key] = (sum(s.get(key, 0) for s in snaps)
                          + retired.get(key, 0))
            d["tokens_per_sec"] = round(
                sum(s["tokens_per_sec"] for s in snaps), 2)
            d["spec_accept_rate"] = (
                round(d["spec_accepted_total"]
                      / d["spec_proposed_total"], 4)
                if d["spec_proposed_total"] else 0.0)
            out[model] = d
        return out

    def prometheus(self) -> str:
        """Fleet-wide rollup under ``{fleet=...}`` plus one per-model
        slice under ``{fleet=..., model=...}`` — same families,
        different label sets (the exposition assembler dedupes the
        per-family TYPE lines, so the one-TYPE-line-per-family pin
        holds)."""
        from horovod_tpu.metrics import render_gauges
        parts = [render_gauges("serve_fleet", self.snapshot(),
                               labels={"fleet": self.fleet})]
        for model, snap in self.snapshot_by_model().items():
            parts.append(render_gauges(
                "serve_fleet", snap,
                labels={"fleet": self.fleet, "model": model}))
        return "".join(parts)


class ServeRouter:
    """N in-process engine replicas behind one admission front door.

    All replicas share the model config, params, mesh, and engine
    geometry — so they share ONE set of jitted programs
    (``make_serve_fns`` memoizes on the geometry) and adding a replica
    costs a KV pool, not a compile.
    """

    def __init__(self, model_cfg, params,
                 router_cfg: Optional[RouterConfig] = None,
                 serve_cfg: Optional[ServeConfig] = None,
                 mesh: Optional[Any] = None, clock=time.perf_counter,
                 workers: Optional[Sequence[Any]] = None,
                 worker_seed: int = 0):
        """``workers`` lifts the fleet across processes: a sequence of
        ``rpc.WorkerHandle`` (from ``rpc.spawn_worker`` /
        ``rpc.connect_worker``), one per replica — each is configured
        with this fleet's model/serve geometry and builds its params
        as ``init_transformer(model_cfg, PRNGKey(worker_seed))``, so
        ``params`` here must equal that (pass ``params=None`` for an
        all-remote fleet; it is only used to build in-process
        engines). With ``workers=None`` every replica is in-process —
        the pre-RPC behavior, byte for byte."""
        self.cfg = router_cfg or RouterConfig()
        self._model_cfg = model_cfg
        self._params = params
        self._serve_cfg = serve_cfg or ServeConfig()
        self._mesh = mesh
        self._clock = clock
        self._worker_seed = worker_seed
        # Registered model groups; the constructor args define the
        # DEFAULT_MODEL group, add_model() registers more (draft/target
        # pairs, A/B fleets, per-tenant models as ordinary members).
        self._models: Dict[str, _ModelGroup] = {
            DEFAULT_MODEL: _ModelGroup(
                model_cfg, params, self._serve_cfg,
                n_prefill=self.cfg.n_prefill, worker_seed=worker_seed)}
        self._rng = np.random.RandomState(self.cfg.seed)
        self._rr = 0                 # round_robin cursor
        self._replicas: List[_Replica] = []
        self._next_instance = itertools.count()
        self._queue: collections.deque[_Pending] = collections.deque()
        self._requests: Dict[int, _Pending] = {}   # every unresolved rid
        # (model, chain entry) -> instance it was last routed to
        # (insertion-ordered for FIFO eviction at CHAIN_INDEX_CAP; the
        # model in the key stops identical token prefixes under
        # different models from aliasing each other's routing hints).
        self._placed_chains: "collections.OrderedDict[Tuple[str, bytes], str]" = \
            collections.OrderedDict()
        self._results: Dict[int, RequestResult] = {}
        self._rids = itertools.count()
        self._retire_ema = RetireEma()
        self.metrics = FleetMetrics(self)
        # Distributed tracing (docs/observability.md): the router's
        # half of every sampled request's timeline. Ids are minted at
        # submit (salted by cfg.seed — deterministic across seeded
        # reruns) and ride the RPC frame header to workers.
        from horovod_tpu.serve.trace import RouterTrace
        self.trace = RouterTrace(clock=clock)
        from horovod_tpu.serve import migrate as migrate_mod
        # "env" resolves the sane-env knob ONCE at fleet construction
        # (a fleet never flips mid-life); "auto"/"off" force it.
        self._direct_mode = (migrate_mod.direct_migration_mode()
                             if self.cfg.direct_migration == "env"
                             else self.cfg.direct_migration)
        # Manifest epochs: every direct-migration attempt carries a
        # fresh one, so a stale partial stream can never replay into
        # a target (the worker refuses repeated epochs).
        self._migration_epochs = itertools.count(1)
        #: (rid, replica instance, chain-match length, link cost in
        #: us) per placement decision, in decision order — the
        #: determinism probe the property test replays. Queue
        #: placements carry cost 0.0 (no source pool to move from);
        #: page-move target picks log match -1 with the decision's
        #: alpha-beta cost verdict. Capped like every other unbounded
        #: series.
        self.placement_log: List[Tuple[int, str, int, float]] = []
        workers = list(workers or [])
        if workers and len(workers) != self.cfg.n_replicas:
            raise ValueError(
                f"{len(workers)} workers for n_replicas="
                f"{self.cfg.n_replicas}; pass one handle per replica")
        for i in range(self.cfg.n_replicas):
            role = ("prefill" if i < self.cfg.n_prefill else
                    "decode" if self.cfg.n_prefill else "unified")
            self._add_replica(role, worker=workers[i] if workers
                              else None)

    # -- membership --------------------------------------------------

    def _add_replica(self, role: str, worker: Any = None,
                     model: str = DEFAULT_MODEL) -> _Replica:
        group = self._models[model]
        inst = str(next(self._next_instance))
        # Router-facing id (`inst`) is per-router and deterministic —
        # placement logs compare bit-for-bit across seeded runs. The
        # EXPOSITION label prefixes the process-unique fleet id: two
        # live fleets must not emit colliding serve_*{instance="0"}
        # samples into one scrape (the exact single-instance collision
        # this PR fixes for engines).
        label = f"{self.metrics.fleet}.{inst}"
        if worker is not None:
            from horovod_tpu.serve.rpc import RemoteReplica
            worker.conn.codec = _codec_id(self.cfg.handoff_compression)
            worker.conn.set_timeout(self.cfg.rpc_timeout)
            eng = RemoteReplica(worker, group.model_cfg,
                                group.serve_cfg,
                                seed=group.worker_seed, instance=label,
                                clock=self._clock, trace=self.trace)
        else:
            if group.params is None:
                raise ValueError(
                    "params=None: cannot build an in-process replica "
                    "(pass params, or a worker handle per replica)")
            eng = ServeEngine(group.model_cfg, group.params,
                              group.serve_cfg, mesh=self._mesh,
                              clock=self._clock, instance=label)
        rep = _Replica(instance=inst, role=role, engine=eng,
                       model=model, remote=worker is not None)
        self._replicas.append(rep)
        from horovod_tpu.common import basics
        _advance_membership(basics.MEMBER_JOIN)
        return rep

    def add_model(self, model: str, model_cfg, params=None,
                  serve_cfg: Optional[ServeConfig] = None, *,
                  n_replicas: int = 1, n_prefill: int = 0,
                  workers: Optional[Sequence[Any]] = None,
                  worker_seed: int = 0) -> List[str]:
        """Register a model group and join its replicas; returns their
        instance ids. Replicas of the new group are ordinary fleet
        members — same placement, drain, shedding, and failover code —
        but requests reach them only via ``submit(..., model=...)``,
        handoffs/migrations stay inside the group, and the per-group
        ``n_prefill`` splits ITS replicas into prefill/decode pools
        independently of the default group's split. This is what makes
        draft/target pairs, A/B fleets, and per-tenant models plain
        members of one fleet. ``workers`` (one handle per replica)
        lifts the group cross-process exactly like the constructor's —
        workers rebuild THIS group's engine via ``configure``."""
        if model in self._models:
            raise ValueError(f"model {model!r} already registered")
        if n_replicas < 1:
            raise ValueError(f"n_replicas {n_replicas} < 1")
        if not 0 <= n_prefill < n_replicas:
            raise ValueError(
                f"n_prefill {n_prefill} must leave at least one decode "
                f"replica out of {n_replicas}")
        workers = list(workers or [])
        if workers and len(workers) != n_replicas:
            raise ValueError(
                f"{len(workers)} workers for n_replicas={n_replicas}; "
                "pass one handle per replica")
        if params is None and not workers:
            raise ValueError(
                "params=None: cannot build in-process replicas for "
                f"model {model!r} (pass params, or a worker handle "
                "per replica)")
        self._models[model] = _ModelGroup(
            model_cfg, params, serve_cfg or ServeConfig(),
            n_prefill=n_prefill, worker_seed=worker_seed)
        out = []
        try:
            for i in range(n_replicas):
                role = ("prefill" if i < n_prefill else
                        "decode" if n_prefill else "unified")
                out.append(self._add_replica(
                    role, worker=workers[i] if workers else None,
                    model=model).instance)
        except Exception:
            # Roll the half-registered group back: a failed worker
            # configure must not leave a zombie model id that can
            # neither be completed nor re-registered.
            self._replicas = [r for r in self._replicas
                              if r.instance not in out]
            del self._models[model]
            raise
        return out

    def add_replica(self, role: Optional[str] = None,
                    model: str = DEFAULT_MODEL) -> str:
        """Join a fresh in-process replica (elastic scale-up); returns
        its instance id. Default role matches the model group's shape:
        "decode" for a split group, "unified" otherwise."""
        return self._join(role, None, model)

    def add_remote_replica(self, worker: Any,
                           role: Optional[str] = None,
                           model: str = DEFAULT_MODEL) -> str:
        """Join a serve-worker process (``rpc.spawn_worker`` /
        ``rpc.connect_worker`` handle) as a replica — the elastic
        scale-up path of the cross-process fleet."""
        return self._join(role, worker, model)

    def _join(self, role: Optional[str], worker: Any,
              model: str = DEFAULT_MODEL) -> str:
        group = self._models.get(model)
        if group is None:
            raise ValueError(f"unknown model {model!r}; registered: "
                             f"{sorted(self._models)}")
        if role is None:
            role = "decode" if group.n_prefill else "unified"
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"unknown role {role!r}")
        return self._add_replica(role, worker=worker,
                                 model=model).instance

    def remove_replica(self, instance: str,
                       migrate_running: bool = False) -> None:
        """Drain a replica out of the fleet: its queued (never
        admitted) requests are withdrawn and requeued at the router
        in original submission order. In-flight sequences either keep
        decoding here until done (the default) or — with
        ``migrate_running=True`` — are exported mid-decode and
        injected into same-model peers with capacity (bitwise page
        moves, same tokens), so a drain completes in O(one step)
        instead of O(longest decode). The replica reaps out once
        empty; a remote replica's worker process is then shut down.

        Guard: refuses to remove the last non-draining replica of a
        needed role *within its model group* when (a) no other group
        has live replicas — an empty fleet serves nothing — or (b)
        the group still has work (router-queued requests for that
        model, or this replica's own in-flight work, which a drain
        with no same-model survivor could never re-place). A workless
        secondary group CAN drain to zero — that is how a model is
        decommissioned."""
        rep = self._replica(instance)
        group = self._models[rep.model]
        peers = [r for r in self._replicas
                 if r is not rep and not r.draining
                 and r.model == rep.model]
        other_groups = any(r.model != rep.model and not r.draining
                           for r in self._replicas)
        needed = (("prefill", "decode") if group.n_prefill
                  else ("unified",))
        for role in needed:
            if rep.role == role and not any(p.role == role
                                            for p in peers):
                queued = any(q.model == rep.model for q in self._queue)
                # Work anywhere in the GROUP blocks the drain, not
                # just this replica's: a peer prefill replica's parked
                # sequence needs a same-model decode target that would
                # never exist again after removing the last one.
                group_work = any(r.outstanding for r in self._replicas
                                 if r.model == rep.model)
                if not other_groups or queued or group_work:
                    raise ValueError(
                        f"cannot remove replica {instance}: last "
                        f"non-draining {role!r} replica for model "
                        f"{rep.model!r}"
                        + (" with queued work" if queued or group_work
                           else " in the fleet"))
        rep.draining = True
        rep.migrate = migrate_running
        # Successful withdrawals stay in `outstanding` until the loop
        # completes: if a later RPC finds the worker dead,
        # _handle_dead requeues EVERYTHING still mapped there — the
        # already-withdrawn included (they can never produce a result
        # on the dead worker), in one correctly-ordered batch. Deleting
        # eagerly would strand those requests in _requests with no
        # queue entry and no owner.
        withdrawn = []
        for erid, rid in list(rep.outstanding.items()):
            ok = self._guard(rep, lambda e=erid: rep.engine.withdraw(e))
            if rep not in self._replicas:
                return   # died mid-drain: _handle_dead requeued it all
            if ok:
                withdrawn.append((erid, rid))
        for erid, _rid in withdrawn:
            del rep.outstanding[erid]
        # Front of the router queue, original submit order preserved:
        # drained work overtakes nothing and loses nothing.
        for req in sorted((self._requests[rid] for _, rid in withdrawn),
                          key=lambda r: r.rid, reverse=True):
            self._queue.appendleft(req)

    def _replica(self, instance: str) -> _Replica:
        for rep in self._replicas:
            if rep.instance == instance:
                return rep
        raise KeyError(f"no replica {instance!r}")

    # -- liveness / failover (cross-process fleet) -------------------

    def _guard(self, rep: _Replica, fn):
        """Run one engine interaction; a transport failure (the
        dead-worker signal) turns into :meth:`_handle_dead` and a
        ``None`` return instead of unwinding the step loop. In-process
        engines never raise it, so this is free for them."""
        from horovod_tpu.serve.rpc import RpcConnectionError
        try:
            return fn()
        except RpcConnectionError:
            self._handle_dead(rep)
            return None

    def _handle_dead(self, rep: _Replica) -> None:
        """A replica's worker is gone. Every request placed there
        whose result was never collected goes back to the FRONT of the
        router queue in original submission order — it re-places on a
        survivor and resolves exactly once (results already collected
        stay collected; the dead worker can no longer deliver
        anything). The replica's last-heartbeat metrics fold into the
        fleet rollup like any reaped replica's."""
        if rep not in self._replicas:
            return
        self._replicas.remove(rep)
        from horovod_tpu.common import basics
        # The numeric instance rides into the native peer_death flight
        # event — a post-mortem dump names WHO died.
        try:
            dead_rank = int(rep.instance)
        except ValueError:
            dead_rank = -1
        _advance_membership(basics.MEMBER_DEAD_PEER, rank=dead_rank)
        _record_flap(f"replica:{self.metrics.fleet}.{rep.instance}")
        getattr(rep.engine, "mark_dead", lambda: None)()
        requeue = [rid for rid in rep.outstanding.values()
                   if rid in self._requests]
        for rid in sorted(requeue, reverse=True):
            self._queue.appendleft(self._requests[rid])
            req = self._requests[rid]
            self.trace.instant("router:requeue", trace=req.trace,
                               rid=rid, from_instance=rep.instance)
        self.metrics.worker_deaths += 1
        self.metrics.requeued_total += len(requeue)
        self.metrics.absorb(rep.engine.metrics, rep.model)
        # Flight trail: one requeue record per orphaned request
        # (a0 = router rid, a1 = dead instance), then — when the
        # operator asked for post-mortems — dump the ring. The native
        # peer_death record from _advance_membership is already in it.
        from horovod_tpu.metrics import flight_dump, flight_record
        for rid in requeue:
            flight_record(basics.FLIGHT_REQUEUE, rid, dead_rank)
        if os.environ.get("HOROVOD_FLIGHT_DIR"):
            flight_dump()

    def _heartbeat_sweep(self, now: float) -> None:
        """Probe remote replicas the step loop will not otherwise talk
        to this iteration (idle ones — a busy replica's ``step`` RPC
        is its heartbeat): liveness, plus the metrics/admission cache
        behind the cross-process fleet scrape. ``heartbeat_every``
        throttles it for real networks; the 0 default keeps every
        step's cache fresh."""
        for rep in list(self._replicas):
            if not rep.remote:
                continue
            if rep.engine.pending:
                continue   # its step() RPC this iteration is the beat
            if now - rep.engine.last_beat < self.cfg.heartbeat_every:
                continue
            self._guard(rep, rep.engine.heartbeat)
            if rep in self._replicas:
                self.metrics.heartbeats += 1

    @property
    def replicas(self) -> List[str]:
        return [r.instance for r in self._replicas]

    @property
    def membership_epoch(self) -> int:
        """The process-global membership epoch after this fleet's
        churn (``hvd.membership().epoch``): joins, drains-to-reap, and
        worker deaths each tick it, alongside any training-plane
        changes in the same process. Monotone — the chaos harness
        asserts exactly that."""
        from horovod_tpu.common import basics
        return int(basics.get_lib().hvd_membership_epoch())

    @property
    def engines(self) -> List[ServeEngine]:
        """The replica engines, fleet order (read-only introspection:
        benchmarks pool latency samples across them)."""
        return [r.engine for r in self._replicas]

    # -- submission / shedding ---------------------------------------

    def _retry_after(self) -> float:
        return self._retire_ema.retry_after(len(self._queue))

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               deadline: Optional[float] = None,
               deadline_class: int = 0,
               model: str = DEFAULT_MODEL) -> int:
        """Fleet admission. Validates against the target model group's
        engine limits, then queues for placement (which only ever
        considers that group's replicas — a request can never land on
        a wrong-model replica, pinned by the router property test).
        On a full router queue the shedding policy runs fleet-wide:
        the newest queued request of a strictly lower class (higher
        number) is shed — resolved to a structured ``"shed"`` result —
        to make room; if none exists, raises
        :class:`FleetSaturated`."""
        prompt = list(prompt)
        group = self._models.get(model)
        if group is None:
            raise ValueError(f"unknown model {model!r}; registered: "
                             f"{sorted(self._models)}")
        cfg = group.serve_cfg
        max_new = (cfg.max_new_tokens if max_new_tokens is None
                   else max_new_tokens)
        # The ENGINE's validation helper, verbatim: anything an engine
        # would reject must reject HERE, not explode out of a later
        # step() at placement time (all replicas of a group share one
        # geometry, so any group engine's pool answers for the group).
        # Draining replicas don't count as live: accepting a request
        # against a group mid-drain-to-zero would queue it forever
        # once the drainer reaps (placement filters draining too).
        mine = [r for r in self._replicas
                if r.model == model and not r.draining]
        if not mine:
            # Every worker died and nothing joined: be explicit
            # instead of IndexError-ing out of validation.
            raise QueueFull(
                f"no live replicas for model {model!r}",
                reason="no_replicas",
                queue_depth=len(self._queue),
                retry_after_s=None)
        validate_request(cfg, group.model_cfg,
                         mine[0].engine.allocator.n_blocks,
                         prompt, max_new, deadline_class)
        if len(self._queue) >= self.cfg.max_queue:
            victim = self._shed_candidate(deadline_class)
            if victim is None:
                self.metrics.record_shed(deadline_class)
                raise FleetSaturated(
                    f"fleet queue full ({self.cfg.max_queue}) and "
                    f"nothing queued is lower-class than "
                    f"{deadline_class}",
                    deadline_class=deadline_class,
                    queue_depth=len(self._queue),
                    retry_after_s=self._retry_after())
            self._shed(victim)
        rid = next(self._rids)
        # Hashed ONCE here, reused by placement scoring, the burst
        # hint, and engine admission (passed through). With the engine
        # tier's caching off there is nothing to be affine TO — no
        # index to walk, no reuse to win — so skip the hashing and let
        # affinity degrade to least-load instead of pinning every
        # same-prefix tenant onto one hot replica for zero benefit.
        chain = (hash_chain(prompt, cfg.block_size)
                 if cfg.prefix_caching else [])
        from horovod_tpu.serve.trace import mint_trace_id
        now = self._clock()
        trace = mint_trace_id(rid, salt=self.cfg.seed)
        req = _Pending(
            rid=rid, prompt=prompt, max_new=max_new, deadline=deadline,
            deadline_class=deadline_class, submitted_at=now,
            chain=chain, model=model, trace=trace)
        self._requests[rid] = req
        self._queue.append(req)
        if trace:
            self.trace.instant("router:submit", t=now, trace=trace,
                               rid=rid, n_prompt=len(prompt),
                               model=model)
        return rid

    def _shed_candidate(self, incoming_class: int) -> Optional[int]:
        """Queue index of the request to shed for an arrival of
        ``incoming_class``: the newest of the *worst* (highest) class,
        and only if strictly worse than the arrival — FIFO favors the
        already-queued at equal class."""
        if not self._queue:
            return None
        worst = max(range(len(self._queue)),
                    key=lambda i: (self._queue[i].deadline_class, i))
        if self._queue[worst].deadline_class <= incoming_class:
            return None
        return worst

    def _shed(self, idx: int) -> None:
        req = self._queue[idx]
        del self._queue[idx]
        del self._requests[req.rid]
        self._results[req.rid] = RequestResult(
            rid=req.rid, status="shed", http_status=503, tokens=[],
            n_prompt=len(req.prompt), submitted_at=req.submitted_at,
            finished_at=self._clock(), reason="shed_low_class",
            deadline_class=req.deadline_class,
            retry_after_s=self._retry_after())
        self.metrics.record_shed(req.deadline_class)

    # -- results -----------------------------------------------------

    def result(self, rid: int) -> Optional[RequestResult]:
        return self._results.get(rid)

    @property
    def results(self) -> Dict[int, RequestResult]:
        return dict(self._results)

    @property
    def pending(self) -> bool:
        return bool(self._queue
                    or any(r.outstanding for r in self._replicas))

    # -- placement ---------------------------------------------------

    def _candidates(
            self, pool_role: Tuple[str, ...], model: str,
    ) -> List[Tuple[_Replica, Dict[str, float]]]:
        """(replica, admission snapshot) pairs eligible for a new
        placement: right MODEL group, right pool, not draining,
        engine-queue room. Model is filtered before anything else —
        capacity pressure in one group can never spill a request onto
        another group's replicas. The affinity invariant — never route
        to a replica without capacity — is enforced here, before any
        cache walk happens; each replica is snapshotted ONCE per
        placement decision and the snapshot rides along for the load
        tie-breaks (it cannot change between filter and pick within
        one decision)."""
        out = []
        for r in list(self._replicas):
            if (r.model != model or r.role not in pool_role
                    or r.draining):
                continue
            snap = self._guard(r, r.engine.admission_snapshot)
            if snap is not None and snap["queue_slots_free"] > 0:
                out.append((r, snap))
        return out

    @staticmethod
    def _load(snap: Dict[str, float]) -> int:
        """Placement-fallback occupancy signal: everything admitted
        or waiting on the snapshotted replica."""
        return int(snap["queue_depth"] + snap["running"]
                   + snap["handoff_parked"])

    def _pick(self, req: _Pending,
              cands: List[Tuple[_Replica, Dict[str, float]]],
              ) -> Tuple[_Replica, int]:
        """Choose among capacity-checked candidates; returns (replica,
        chain_match_len). Deterministic for a fixed seed: ties break
        on load then list order, and the random baseline draws from
        the config-seeded RNG."""
        if self.cfg.placement == "random":
            return cands[int(self._rng.randint(len(cands)))][0], 0
        if self.cfg.placement == "round_robin":
            rep = cands[self._rr % len(cands)][0]
            self._rr += 1
            return rep, 0
        if self.cfg.placement == "affinity":
            scored = [(self._chain_score(r, req.chain), r, s)
                      for r, s in cands]
            best = max(n for n, _, _ in scored)
            if best > 0:
                hot = [(r, s) for n, r, s in scored if n == best]
                return min(hot, key=lambda t: self._load(t[1]))[0], best
        return min(cands, key=lambda t: self._load(t[1]))[0], 0

    def _chain_score(self, rep: _Replica, chain: List[bytes]) -> int:
        """Affinity score of ``rep`` for a prompt chain: the longer of
        the replica's LIVE content-index walk (blocks actually held)
        and the leading run of chain entries last ROUTED there (the
        burst hint — a same-prefix sibling placed moments ago whose
        prefill hasn't published yet). Hint keys carry the model id,
        so identical prefixes under different models never alias."""
        live = self._guard(
            rep, lambda: rep.engine.cached_chain_len(chain))
        if live is None:
            # Died mid-walk: score 0; the placement pass discovers the
            # death at submit (or the replica-count check) and
            # restarts against the survivors.
            return 0
        hint = 0
        for h in chain:
            if self._placed_chains.get((rep.model, h)) != rep.instance:
                break
            hint += 1
        return max(live, hint)

    def _record_chain(self, rep: _Replica, chain: List[bytes]) -> None:
        for h in chain:
            key = (rep.model, h)
            if key in self._placed_chains:
                self._placed_chains.move_to_end(key)
            self._placed_chains[key] = rep.instance
        while len(self._placed_chains) > CHAIN_INDEX_CAP:
            self._placed_chains.popitem(last=False)

    def _place_queued(self) -> None:
        """FIFO placement (no overtaking — same tail-predictability
        contract as engine admission): place in queue order until a
        MODEL's requests find no candidate, then skip that model's
        remaining requests this step and keep placing other models' —
        FIFO holds within each model group, but one saturated (or
        replica-less) group never head-of-line-blocks the rest of the
        fleet. Pool roles come from the request's group (each group
        splits prefill/decode independently); candidates are always
        same-model."""
        # Snapshot scan, one rid-filtered rebuild per pass: a worker
        # death inside a _guard call requeues its work at the queue
        # FRONT mid-scan, so positional indexing could place one
        # request and delete a different one — and per-placement
        # deque.remove would make a deep queue O(n^2). A death
        # RESTARTS the pass from the (mutated) front, so per-model
        # FIFO holds even across failovers: the requeued-at-front work
        # and the request whose pick died both go before anything
        # younger.
        while True:
            stuck: set = set()    # models with no candidate this pass
            placed: set = set()   # rids placed this pass
            n_reps = len(self._replicas)
            died = False
            for req in list(self._queue):
                if req.model in stuck:
                    continue
                group = self._models[req.model]
                pool = (("prefill",) if group.n_prefill
                        else ("unified",))
                cands = self._candidates(pool, req.model)
                if len(self._replicas) != n_reps:
                    # A death detected inside the candidate probes (or
                    # the affinity walk) requeued work at the front —
                    # restart so it is not overtaken by this pass's
                    # stale snapshot.
                    died = True
                    break
                if not cands:
                    stuck.add(req.model)
                    continue
                rep, match = self._pick(req, cands)
                t_place = self._clock()
                erid = self._guard(rep, lambda: rep.engine.submit(
                    req.prompt, req.max_new, deadline=req.deadline,
                    deadline_class=req.deadline_class,
                    prefill_only=(rep.role == "prefill"),
                    chain=req.chain, trace_id=req.trace))
                if erid is None:
                    died = True
                    break
                if req.trace:
                    # Queue wait closes at placement: submit -> the
                    # instant the request left the router queue.
                    self.trace.span(
                        "router:queue_wait", req.submitted_at,
                        t_place - req.submitted_at, trace=req.trace,
                        rid=req.rid, instance=rep.instance,
                        match=match)
                placed.add(req.rid)
                rep.outstanding[erid] = req.rid
                if self.cfg.placement == "affinity":
                    # Only the affinity scorer ever reads the hint
                    # index; the baselines skip the OrderedDict churn.
                    self._record_chain(rep, req.chain)
                self.metrics.record_placed(match)
                if len(self.placement_log) < MAX_SAMPLES:
                    self.placement_log.append(
                        (req.rid, rep.instance, match, 0.0))
            if placed:
                # A death mid-pass UN-places work: _handle_dead
                # requeued every rid the dead replica owned — including
                # ones placed earlier in THIS pass (the queue then
                # holds the same _Pending twice: stale position +
                # requeued front). Keep anything no longer owned by a
                # live replica, deduped to its front (requeued)
                # occurrence so requeue-at-front order survives.
                owned = {rid for r in self._replicas
                         for rid in r.outstanding.values()}
                placed &= owned
                seen: set = set()
                newq: collections.deque = collections.deque()
                for q in self._queue:
                    if q.rid in placed or q.rid in seen:
                        continue
                    seen.add(q.rid)
                    newq.append(q)
                self._queue = newq
            if not died:
                return

    # -- handoff (prefill pool -> decode pool) -----------------------

    def _collect_handoffs(self) -> None:
        for rep in list(self._replicas):
            if rep.role != "prefill":
                continue
            ready = self._guard(rep, rep.engine.handoff_ready)
            if ready is None:
                continue   # died; _handle_dead requeued its work
            for erid in ready:
                rid = rep.outstanding[erid]
                req = self._requests[rid]
                need = rep.engine.allocator.blocks_for_tokens(
                    len(req.prompt) + req.max_new)
                target = self._pick_capacity(("decode",), need,
                                             exclude=rep,
                                             model=rep.model,
                                             source=rep)
                if target is None:
                    # No decode capacity this step; the sequence stays
                    # parked (blocks held at the prefill replica) and
                    # is retried next step — never dropped.
                    continue
                if not self._move_seq(rep, erid, rid, target,
                                      "prefilled", need):
                    if rep not in self._replicas:
                        break   # source died; its work is requeued
                    continue
                self.metrics.handoffs += 1

    def _migrate_draining(self) -> None:
        """The migrating half of ``remove_replica(migrate_running=
        True)``: export RUNNING sequences off draining replicas and
        inject them into same-pool peers with capacity (a bitwise page
        move — the tokens that follow are exactly the ones the donor
        would have produced). A sequence with no target this step
        keeps decoding on the drainer and retries next step — never
        dropped, never duplicated."""
        for rep in list(self._replicas):
            if not (rep.draining and rep.migrate):
                continue
            running = self._guard(rep, rep.engine.running_exportable)
            if running is None:
                continue
            pool = (("decode",)
                    if self._models[rep.model].n_prefill
                    else ("unified",))
            for erid in running:
                rid = rep.outstanding.get(erid)
                if rid is None:
                    continue   # e.g. injected seq finishing this step
                req = self._requests[rid]
                need = rep.engine.allocator.blocks_for_tokens(
                    len(req.prompt) + req.max_new)
                target = self._pick_capacity(pool, need, exclude=rep,
                                             model=rep.model,
                                             source=rep)
                if target is None:
                    continue
                if not self._move_seq(rep, erid, rid, target,
                                      "running", need):
                    if rep not in self._replicas:
                        break
                    continue
                self.metrics.migrations += 1

    def _migration_plan(self, src: _Replica, target: _Replica,
                        need_blocks: int) -> Dict[str, Any]:
        """Chunk-schedule verdict for moving ``need_blocks`` worth of
        pages src -> target: the Python cost twin over the measured
        alpha-beta model (mirrored by the native
        ``hvd_migration_cost_us``). No model (tier-1 fleets, single
        hosts) degrades to the default chunking with cost 0."""
        from horovod_tpu.serve import migrate as migrate_mod
        topo = migrate_mod.fleet_topology()
        n_ranks = int(topo["np"]) if topo else 0
        return migrate_mod.plan_migration(
            need_blocks,
            migrate_mod.page_nbytes(
                self._models[src.model].model_cfg,
                src.engine.allocator.block_size),
            src=migrate_mod.replica_rank(src.instance, n_ranks),
            dst=migrate_mod.replica_rank(target.instance, n_ranks),
            codec=self.cfg.handoff_compression, model=topo)

    def _note_migration(self, rid: int, target: _Replica,
                        cost_us: float, wire_bytes: int,
                        ms: float) -> None:
        m = self.metrics
        m.migration_bytes_total += int(wire_bytes)
        m.record_migration_ms(ms)
        m.migration_link_cost_us = round(float(cost_us), 3)
        if len(self.placement_log) < MAX_SAMPLES:
            # match -1 marks a page-move target pick (vs a queue
            # placement); the cost column is the decision's verdict.
            self.placement_log.append(
                (rid, target.instance, -1, round(float(cost_us), 3)))

    def _move_seq(self, src: _Replica, erid: int, rid: int,
                  target: _Replica, kind: str,
                  need_blocks: int) -> bool:
        """Move sequence ``erid`` (``kind`` = "prefilled" | "running")
        off ``src`` and into ``target``.

        With the direct plane on and both ends remote, the router
        sends ONE control frame (``migrate_to``) and the source
        streams the pages point-to-point to the target's bulk
        listener, chunked per the topology plan — the bytes never
        visit this process. A failed dial falls back to the relayed
        export->inject below, byte-compatible.

        Failure semantics keep exactly-once on every path: an export
        that dies takes the whole source down (its outstanding work —
        this rid included — requeues); a stream or inject that dies
        AFTER the export freed the source pages requeues THIS request
        explicitly at the queue front (its pages died in flight; it
        re-prefills from scratch on a survivor), while the target
        discards its partial pages by staging-abort."""
        t0 = self._clock()
        plan = self._migration_plan(src, target, need_blocks)
        if (self._direct_mode == "auto" and src.remote and target.remote
                and src is not target
                and getattr(target.engine, "peer_port", 0)):
            ret = self._guard(src, lambda: src.engine.migrate_direct(
                erid, kind, target.engine.peer_host,
                target.engine.peer_port, plan["chunk_pages"],
                next(self._migration_epochs)))
            if ret is None:
                return False     # source died: _handle_dead requeued
            status = ret.get("status")
            if status == "ok":
                del src.outstanding[erid]
                target.outstanding[int(ret["erid"])] = rid
                target.engine.note_remote_inject()
                self.metrics.direct_migrations_total += 1
                self._note_migration(
                    rid, target, cost_us=plan["cost_us"],
                    wire_bytes=int(ret.get("wire_bytes") or 0),
                    ms=float(ret.get("ms") or 0.0))
                self._trace_handoff(rid, src, target, kind, t0)
                return True
            if status != "dial_failed":
                # Exported, then the stream died mid-transfer: pages
                # are gone on both sides (target staging aborted on
                # disconnect). Queue front, exactly-once.
                del src.outstanding[erid]
                self._queue.appendleft(self._requests[rid])
                self.metrics.requeued_total += 1
                return False
            # dial_failed: the sequence never left the source — fall
            # through to the relayed path.
        h = self._guard(src,
                        lambda: getattr(src.engine,
                                        f"export_{kind}")(erid))
        if h is None:
            return False
        del src.outstanding[erid]
        new_erid = self._guard(target,
                               lambda: target.engine.inject_prefilled(h))
        if new_erid is None:
            self._queue.appendleft(self._requests[rid])
            self.metrics.requeued_total += 1
            return False
        target.outstanding[new_erid] = rid
        # Relayed accounting: the pages crossed the router, raw (span
        # codec applies per hop on remote ends; nbytes here is the
        # router-held copy — one traversal's worth for parity with
        # the direct counter).
        self._note_migration(
            rid, target, cost_us=plan["cost_us"],
            wire_bytes=int(np.asarray(h.k_pages).nbytes
                           + np.asarray(h.v_pages).nbytes),
            ms=(self._clock() - t0) * 1e3)
        self._trace_handoff(rid, src, target, kind, t0)
        return True

    def _trace_handoff(self, rid: int, src: _Replica,
                       target: _Replica, kind: str, t0: float) -> None:
        req = self._requests.get(rid)
        if req is None or not req.trace:
            return
        self.trace.span("router:handoff", t0, self._clock() - t0,
                        trace=req.trace, rid=rid, kind=kind,
                        src=src.instance, dst=target.instance)

    def _pick_capacity(self, pool_role: Tuple[str, ...],
                       need_blocks: int,
                       exclude: Optional[_Replica] = None,
                       model: str = DEFAULT_MODEL,
                       source: Optional[_Replica] = None,
                       ) -> Optional[_Replica]:
        """Cheapest-link, then least-loaded same-MODEL replica in
        ``pool_role`` with a batch slot AND ``need_blocks`` of KV
        headroom — the handoff/migration target filter
        (admission-queue room is irrelevant: an injected sequence
        bypasses the queue). With a measured topology model and a
        ``source``, candidates are scored by the alpha-beta cost of
        moving the pages over their link first (a drain on a
        multi-host fleet prefers the cheap link); without a model —
        tier-1 fleets, single hosts — every cost is 0 and the pick is
        the historical pure least-load. Pages only ever move between
        replicas of one model group: a KV page is meaningless under
        another model's weights."""
        from horovod_tpu.serve import migrate as migrate_mod
        topo = migrate_mod.fleet_topology() if source is not None \
            else None
        n_ranks = int(topo["np"]) if topo else 0
        src_rank = (migrate_mod.replica_rank(source.instance, n_ranks)
                    if source is not None else 0)
        xfer_bytes = 0
        if topo is not None:
            xfer_bytes = int(
                need_blocks
                * migrate_mod.page_nbytes(
                    self._models[model].model_cfg,
                    source.engine.allocator.block_size)
                * migrate_mod.codec_wire_ratio(
                    self.cfg.handoff_compression))
        cands = []
        for r in list(self._replicas):
            if (r.model != model or r.role not in pool_role
                    or r.draining or r is exclude):
                continue
            snap = self._guard(r, r.engine.admission_snapshot)
            if (snap is not None and snap["batch_slots_free"] > 0
                    and r.engine.allocator.can_alloc(need_blocks)):
                cost = migrate_mod.link_cost_us(
                    topo, src_rank,
                    migrate_mod.replica_rank(r.instance, n_ranks),
                    xfer_bytes)
                cands.append((r, snap, cost))
        if not cands:
            return None
        return min(cands, key=lambda t: (round(t[2], 3),
                                         self._load(t[1])))[0]

    # -- the fleet iteration -----------------------------------------

    def step(self) -> None:
        """One fleet iteration: heartbeat idle remote replicas
        (liveness + the cross-process metrics cache), expire
        router-queued deadlines, move completed prefills to the decode
        pool, migrate RUNNING work off migrating drains, place queued
        requests, step every busy replica, collect results, reap
        drained replicas. A worker that died since the last step is
        detected at its first RPC this step and its uncollected work
        requeues at the front — nothing is dropped, nothing resolves
        twice."""
        now = self._clock()
        self._heartbeat_sweep(now)
        self._expire_queued(now)
        self._collect_handoffs()
        self._migrate_draining()
        self._place_queued()
        self._step_replicas()
        self._collect_results()
        self._reap_drained()

    def _step_replicas(self) -> None:
        """Step every busy replica. Remote replicas' step RPCs FAN
        OUT: the request frame goes to every busy worker first
        (``step_begin``), in-process replicas step while the workers
        compute, then the replies are collected — and applied — in
        fleet order (``step_finish``). N workers therefore run their
        iterations concurrently instead of serially per router step
        (the measured loopback RPC tax was ~0.8x serial), while reply
        application order stays the deterministic fleet order — never
        network arrival order — so placement logs and results remain
        seed-deterministic. A worker that died is detected at its send
        OR its reply; either way ``_handle_dead`` requeues its work
        exactly once."""
        started: List[_Replica] = []
        try:
            # Remote begins FIRST (all of them), in-process steps
            # second: the workers compute while the local engines run,
            # instead of a leading local replica's full decode step
            # delaying every worker's request frame.
            for rep in list(self._replicas):
                if (rep in self._replicas and rep.remote
                        and rep.engine.pending):
                    if self._guard(rep,
                                   rep.engine.step_begin) is not None:
                        started.append(rep)
            for rep in list(self._replicas):
                if (rep in self._replicas and not rep.remote
                        and rep.engine.pending):
                    self._guard(rep, rep.engine.step)
            while started:
                rep = started.pop(0)
                if rep in self._replicas:
                    self._guard(rep, rep.engine.step_finish)
        except BaseException:
            # A non-transport failure mid-fan-out (_guard only absorbs
            # connection errors — e.g. a worker engine exception
            # re-raised natively): the replicas still in `started`
            # have an uncollected step reply on a STRICT
            # request/response connection. Drain those replies
            # best-effort before unwinding, or the next RPC on each
            # would read a stale step beat as its own reply.
            for rep in started:
                if rep in self._replicas:
                    try:
                        rep.engine.step_finish()
                    except Exception:
                        pass
            raise

    def _expire_queued(self, now: float) -> None:
        keep: collections.deque[_Pending] = collections.deque()
        for req in self._queue:
            if req.deadline is not None and now > req.deadline:
                del self._requests[req.rid]
                self._results[req.rid] = RequestResult(
                    rid=req.rid, status="expired", http_status=503,
                    tokens=[], n_prompt=len(req.prompt),
                    submitted_at=req.submitted_at, finished_at=now,
                    reason="deadline_expired",
                    deadline_class=req.deadline_class,
                    retry_after_s=self._retry_after())
                self.metrics.expired_total += 1
            else:
                keep.append(req)
        self._queue = keep

    def _collect_results(self) -> None:
        for rep in self._replicas:
            done = []
            for erid, rid in rep.outstanding.items():
                res = rep.engine.result(erid)
                if res is None:
                    continue
                # Rebind to the router's rid space; everything else
                # (tokens, latencies, structured-rejection fields)
                # passes through untouched.
                req = self._requests[rid]
                self._results[rid] = dataclasses.replace(res, rid=rid)
                del self._requests[rid]
                done.append(erid)
                if req.trace:
                    # End-to-end on the router clock: submit to the
                    # step the result came home. The critical-path
                    # breakdown in `hvd-trace` decomposes exactly
                    # this span.
                    t_end = self._clock()
                    self.trace.span(
                        "router:e2e", req.submitted_at,
                        t_end - req.submitted_at, trace=req.trace,
                        rid=rid, status=res.status,
                        instance=rep.instance)
                # Only REAL retirements feed the drain-rate EMA (the
                # engine's own EMA observes only _finish): a deadline
                # storm of back-to-back expirations would otherwise
                # collapse retry_after_s toward 0 exactly when the
                # fleet is saturated and serving nothing.
                if res.status == "ok" and res.finished_at is not None:
                    self._retire_ema.observe(res.finished_at)
            for erid in done:
                del rep.outstanding[erid]

    def _reap_drained(self) -> None:
        for r in list(self._replicas):
            if not (r.draining and not r.outstanding
                    and not r.engine.pending):
                continue
            parked = self._guard(r, r.engine.handoff_ready)
            if r not in self._replicas or parked:
                continue   # died (handled) or still holding handoffs
            # Fold the dying replica's lifetime counters and latency
            # samples into the rollup — fleet totals and tails must
            # survive membership churn — then, for a worker process,
            # shut it down (the drain owns the worker's lifecycle).
            self.metrics.absorb(r.engine.metrics, r.model)
            self._replicas.remove(r)
            from horovod_tpu.common import basics
            _advance_membership(basics.MEMBER_SHRINK)
            if r.remote:
                r.engine.shutdown()

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        for _ in range(max_steps):
            if not self.pending:
                return
            self.step()
        raise RuntimeError(f"fleet still busy after {max_steps} steps")

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: Optional[int] = None) -> List[List[int]]:
        """Convenience batch API, mirroring ``ServeEngine.generate``:
        serve ``prompts`` across the fleet and return their token
        streams in submission order."""
        rids = [self.submit(p, max_new_tokens) for p in prompts]
        self.run_until_idle()
        return [self._results[r].tokens for r in rids]

    def export_fleet_trace(self, dir_path: str) -> List[str]:
        """Write the whole fleet's trace files into ``dir_path``:
        ``router.json`` (this router's spans + timebase anchor) and
        one ``replica-<instance>.json`` per live replica, each
        carrying its own anchor and — for remote replicas — the
        router's RTT-estimated clock offset. ``bin/hvd-trace merge``
        over the directory produces the single-timebase Perfetto
        view. Returns the paths written. Remote replicas with no
        offset sample yet get one forced heartbeat first (a fleet
        that never idled may never have swept them)."""
        import json as _json
        os.makedirs(dir_path, exist_ok=True)
        paths = []
        p = os.path.join(dir_path, "router.json")
        self.trace.export(p, fleet=self.metrics.fleet)
        paths.append(p)
        for rep in list(self._replicas):
            p = os.path.join(dir_path,
                             f"replica-{rep.instance}.json")
            if rep.remote:
                if rep.engine.clock_rtt == float("inf"):
                    self._guard(rep, rep.engine.heartbeat)
                    if rep not in self._replicas:
                        continue   # died on the forced beat
                d = self._guard(rep, rep.engine.export_trace)
                if d is None:
                    continue
                with open(p, "w") as f:
                    _json.dump({"traceEvents": d["events"],
                                "displayTimeUnit": "ms",
                                "metadata": d["meta"]}, f)
            else:
                rep.engine.metrics.export_chrome_trace(
                    p, instance=rep.instance, clock_offset=0.0)
            paths.append(p)
        return paths

    def close(self) -> None:
        """Release remote replicas without drain semantics: best-
        effort shutdown RPC to every worker, connections closed.
        In-process replicas need no teardown. Idempotent; the
        cross-process bench/tests call it between cold fleets."""
        for rep in self._replicas:
            if rep.remote:
                rep.engine.shutdown()
