"""RPC plane for the cross-process serving fleet.

The PR 8 router talks to replicas through a narrow seam
(``admission_snapshot`` / ``submit`` / ``withdraw`` /
``export_prefilled`` + ``inject_prefilled`` plus ``step``/``drain``)
that was designed to be lifted to RPC. This module lifts it: a
length-prefixed, versioned framing over the native vectored transport
(the ``hvd_tcp_sendv``/``hvd_tcp_recvv`` ctypes surface from PR 10 —
the same syscall paths the collective data plane runs), a small
struct-packed value codec (msgpack-free: tagged scalars/containers
inline in the frame, numpy tensors as raw spans AFTER the frame so
bulk K/V pages ride one ``SendV`` span list and land via ``RecvV``
directly in their destination buffers), and the client tier the
router consumes: :class:`RpcConn` (one blocking request/response
channel), :class:`RemoteReplica` (the engine seam re-exposed over a
connection — the router treats it identically to an in-process
``ServeEngine``), and :func:`spawn_worker` (launch + connect a
``horovod_tpu.serve.worker`` process).

Wire layout of one message::

    [u64 frame_len][frame: u32 magic | u16 version | u16 n_spans |
                    u64 trace_id | packed body]
    [span 0 bytes]...[span n-1 bytes]

The ``trace_id`` header field (protocol v2) carries the distributed
tracing context of :mod:`horovod_tpu.serve.trace`: the router stamps
the request's trace id on the frame that places it (``submit`` /
``inject_prefilled``), the worker reads it off the header
(:attr:`RpcConn.last_trace_id`) and tags its engine spans. 0 = no
trace context (the overwhelmingly common frame).

The body is the request/response value tree; every numpy array in the
tree is replaced by a struct-packed descriptor ``(codec, dtype, shape,
wire_bytes)`` and its bytes shipped as span ``i`` in tree order. The
whole message goes out as ONE vectored send (prefix, frame, and all
spans in a single ``SendV`` span list — the framing is invisible to
iovec boundaries, exactly the PR 10 contract), and the receiver drains
every span with ONE ``RecvV`` straight into the freshly-allocated
destination arrays: no intermediate concatenation buffer on either
side.

**KV-page compression.** A span whose source array is float32 and at
least :data:`SPAN_CODEC_MIN_ELEMS` elements long may be encoded with
the PR 9 wire codecs (``bf16``/``fp16`` — the cast codecs; int8 needs
error-feedback state that has no meaning for one-shot page migration)
via the native ``hvd_wire_encode``/``hvd_wire_decode`` kernels: bf16
halves migration bytes, and the decode is the same bitwise-pinned
multiply-free cast the TCP collective plane ships, so a compressed
handoff is deterministic (encode→decode is exactly the numpy
bf16-roundtrip, pinned by tests/test_rpc.py). The codec rides the
span descriptor, so the receiver needs no configuration.

Versioning: :data:`RPC_PROTOCOL_VERSION` is single-sourced HERE (the
same discipline as the ``kWireVersion*`` pins in ``basics.py`` —
``tools/lint`` enforces that no other module redefines it) and checked
on every received frame; a mismatch raises :class:`RpcProtocolError`
before any body parsing happens.

No jax import at module scope: the framing tier is importable (and
unit-testable over socketpairs) without paying the engine's
dependencies.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import struct
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu.common.basics import dtype_id, get_lib, np_dtype

#: RPC protocol version, checked on every frame. Single definition
#: site (lint rule ``abi-literal`` treats it like the wire-version
#: pins): bump on ANY change to the frame header, the value-codec
#: tags, or the span descriptor layout.
#: v2: the frame header grew a u64 ``trace_id`` after ``n_spans``
#: (distributed request tracing, serve/trace.py) — same magic, same
#: leading fields, so a v1 peer is detected and named before the new
#: field is ever parsed.
RPC_PROTOCOL_VERSION = 2

#: Frame magic ("HRPC", little-endian).
RPC_MAGIC = 0x43505248

#: Sanity cap on one frame's byte length (the body only — tensor spans
#: ride outside the frame, so frames stay small; a corrupt or
#: misaligned length prefix fails here instead of allocating garbage).
MAX_FRAME_BYTES = 64 << 20

#: Below this element count a float32 array ships raw even when a span
#: codec is configured: the encode dispatch costs more than it saves.
SPAN_CODEC_MIN_ELEMS = 256

# Native WireCodec ids accepted for span encoding (codec.h; the int8
# codec carries error-feedback semantics that make no sense for
# one-shot page migration, so it is rejected at configuration time).
_SPAN_CODECS = {"none": 0, "bf16": 1, "fp16": 2}


class RpcError(RuntimeError):
    """Base class for RPC-plane failures."""


class RpcConnectionError(RpcError):
    """The peer is gone (EOF, reset, timeout): the router's
    dead-worker signal. Any call that raises this leaves the
    connection unusable."""


class RpcProtocolError(RpcError):
    """The peer speaks a different protocol (bad magic or version
    mismatch) — fail loudly before parsing anything."""


class RpcRemoteError(RpcError):
    """A remote handler raised an exception type this side cannot
    reconstruct; carries the remote type name and message."""

    def __init__(self, exc_type: str, msg: str,
                 fields: Optional[Dict[str, Any]] = None):
        super().__init__(f"{exc_type}: {msg}")
        self.exc_type = exc_type
        self.fields = fields or {}


def span_codec_id(name) -> int:
    """Map a KV-handoff compression spelling (None / "bf16" / "fp16" /
    a ``hvd.Compression`` member) to the native span codec id."""
    if name is None:
        return 0
    wire = getattr(name, "wire_codec", None)
    if wire is not None:          # a Compression member
        name = {0: "none", 1: "bf16", 2: "fp16", 3: "int8"}.get(int(wire))
    try:
        return _SPAN_CODECS[str(name)]
    except KeyError:
        raise ValueError(
            f"unsupported KV handoff compression {name!r}; want one of "
            f"{sorted(_SPAN_CODECS)} (int8 needs error-feedback state "
            "that one-shot page migration has nowhere to keep)") from None


# ---------------------------------------------------------------------------
# Value codec: tagged, struct-packed, msgpack-free.
# ---------------------------------------------------------------------------

_T_NONE, _T_TRUE, _T_FALSE, _T_INT, _T_FLOAT = 0, 1, 2, 3, 4
_T_BYTES, _T_STR, _T_LIST, _T_DICT, _T_ARRAY = 5, 6, 7, 8, 9
_T_U64 = 10  # ints in [2**63, 2**64): 64-bit ids (FNV-1a trace ids)


class _ArrayStub:
    """Placeholder for a tensor span while its bytes are in flight."""

    __slots__ = ("codec", "dtype", "shape", "wire_bytes", "buf", "arr")

    def __init__(self, codec, dtype, shape, wire_bytes):
        self.codec = codec
        self.dtype = dtype
        self.shape = shape
        self.wire_bytes = wire_bytes
        if codec:
            # Validate the declared span size against what the codec
            # REQUIRES for this shape before the native decode runs —
            # a short buffer would otherwise be an out-of-bounds read
            # inside hvd_wire_decode, not a clean protocol error.
            elems = int(np.prod(shape, dtype=np.int64)) if shape else 1
            want = int(get_lib().hvd_wire_encoded_bytes(codec, elems))
            if wire_bytes != want:
                raise RpcProtocolError(
                    f"codec-{codec} span declares {wire_bytes} wire "
                    f"bytes but shape {shape} needs {want}")
            # Encoded payload lands here; decoded after the RecvV.
            self.buf = np.empty(wire_bytes, np.uint8)
            self.arr = None
        else:
            # Raw payload lands DIRECTLY in the destination array.
            self.arr = np.empty(shape, dtype)
            self.buf = self.arr
            if self.arr.nbytes != wire_bytes:
                raise RpcProtocolError(
                    f"span byte count {wire_bytes} != {self.arr.nbytes} "
                    f"for shape {shape} dtype {dtype}")

    def resolve(self, lib) -> np.ndarray:
        if self.codec:
            out = np.empty(self.shape, np.float32)
            lib.hvd_wire_decode(
                self.codec,
                ctypes.c_void_p(self.buf.ctypes.data), out.size,
                ctypes.c_void_p(out.ctypes.data))
            self.arr = out
        return self.arr


def _pack_value(obj, out: List[bytes],
                spans: List[Tuple[np.ndarray, int]], codec: int) -> None:
    if obj is None:
        out.append(struct.pack("<B", _T_NONE))
    elif obj is True:
        out.append(struct.pack("<B", _T_TRUE))
    elif obj is False:
        out.append(struct.pack("<B", _T_FALSE))
    elif isinstance(obj, (int, np.integer)):
        v = int(obj)
        if -(1 << 63) <= v < (1 << 63):
            out.append(struct.pack("<Bq", _T_INT, v))
        elif (1 << 63) <= v < (1 << 64):
            out.append(struct.pack("<BQ", _T_U64, v))
        else:
            raise TypeError(
                f"rpc value codec cannot marshal {v}: wider than 64 bits")
    elif isinstance(obj, (float, np.floating)):
        out.append(struct.pack("<Bd", _T_FLOAT, float(obj)))
    elif isinstance(obj, bytes):
        out.append(struct.pack("<BI", _T_BYTES, len(obj)))
        out.append(obj)
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.append(struct.pack("<BI", _T_STR, len(b)))
        out.append(b)
    elif isinstance(obj, np.ndarray):
        _pack_array(obj, out, spans, codec)
    elif isinstance(obj, (list, tuple)):
        out.append(struct.pack("<BI", _T_LIST, len(obj)))
        for v in obj:
            _pack_value(v, out, spans, codec)
    elif isinstance(obj, dict):
        out.append(struct.pack("<BI", _T_DICT, len(obj)))
        for k, v in obj.items():
            _pack_value(k, out, spans, codec)
            _pack_value(v, out, spans, codec)
    else:
        raise TypeError(
            f"rpc value codec cannot marshal {type(obj).__name__}; "
            "use scalars, bytes, str, lists, dicts, or numpy arrays")


def _pack_array(a: np.ndarray, out: List[bytes],
                spans: List[Tuple[np.ndarray, int]], codec: int) -> None:
    a = np.asarray(a)
    if not a.flags["C_CONTIGUOUS"]:
        # NOT ascontiguousarray: that helper promotes 0-d to 1-d and
        # would silently change the echoed shape.
        a = np.ascontiguousarray(a).reshape(a.shape)
    use_codec = (codec != 0 and a.dtype == np.float32
                 and a.size >= SPAN_CODEC_MIN_ELEMS)
    if use_codec:
        lib = get_lib()
        wire_n = int(lib.hvd_wire_encoded_bytes(codec, a.size))
        payload = np.empty(wire_n, np.uint8)
        lib.hvd_wire_encode(
            codec, ctypes.c_void_p(a.ctypes.data), a.size,
            ctypes.c_void_p(payload.ctypes.data), None)
        cid = codec
    else:
        payload, cid = a, 0
    out.append(struct.pack("<BBB", _T_ARRAY, cid, dtype_id(a.dtype)))
    out.append(struct.pack("<B", a.ndim))
    out.append(struct.pack(f"<{a.ndim}q", *a.shape))
    out.append(struct.pack("<Q", payload.nbytes))
    spans.append((payload, a.nbytes))


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def take(self, fmt):
        vals = struct.unpack_from(fmt, self.buf, self.pos)
        self.pos += struct.calcsize(fmt)
        return vals

    def take_bytes(self, n):
        b = bytes(self.buf[self.pos:self.pos + n])
        if len(b) != n:
            raise RpcProtocolError("truncated frame body")
        self.pos += n
        return b


def _unpack_value(r: _Reader, stubs: List[_ArrayStub]):
    (tag,) = r.take("<B")
    if tag == _T_NONE:
        return None
    if tag == _T_TRUE:
        return True
    if tag == _T_FALSE:
        return False
    if tag == _T_INT:
        return r.take("<q")[0]
    if tag == _T_U64:
        return r.take("<Q")[0]
    if tag == _T_FLOAT:
        return r.take("<d")[0]
    if tag == _T_BYTES:
        return r.take_bytes(r.take("<I")[0])
    if tag == _T_STR:
        return r.take_bytes(r.take("<I")[0]).decode("utf-8")
    if tag == _T_LIST:
        (n,) = r.take("<I")
        return [_unpack_value(r, stubs) for _ in range(n)]
    if tag == _T_DICT:
        (n,) = r.take("<I")
        out = {}
        for _ in range(n):
            k = _unpack_value(r, stubs)
            out[k] = _unpack_value(r, stubs)
        return out
    if tag == _T_ARRAY:
        cid, did = r.take("<BB")
        (ndim,) = r.take("<B")
        shape = r.take(f"<{ndim}q") if ndim else ()
        (wire_bytes,) = r.take("<Q")
        stub = _ArrayStub(cid, np_dtype(did), tuple(shape), wire_bytes)
        stubs.append(stub)
        return stub
    raise RpcProtocolError(f"unknown value tag {tag}")


def _resolve_stubs(obj, lib):
    if isinstance(obj, _ArrayStub):
        return obj.resolve(lib)
    if isinstance(obj, list):
        return [_resolve_stubs(v, lib) for v in obj]
    if isinstance(obj, dict):
        return {k: _resolve_stubs(v, lib) for k, v in obj.items()}
    return obj


# ---------------------------------------------------------------------------
# Connection
# ---------------------------------------------------------------------------

def _as_iovec(chunks):
    n = len(chunks)
    bufs = (ctypes.c_void_p * n)()
    lens = (ctypes.c_uint64 * n)()
    keep = []
    for i, c in enumerate(chunks):
        if isinstance(c, np.ndarray):
            bufs[i] = ctypes.c_void_p(c.ctypes.data)
            lens[i] = c.nbytes
        else:
            bufs[i] = ctypes.cast(ctypes.c_char_p(c), ctypes.c_void_p)
            lens[i] = len(c)
        keep.append(c)   # hold references across the syscall
    return bufs, lens, n, keep


class RpcConn:
    """One blocking request/response RPC channel over a connected
    socket, driven through the native vectored transport. Not
    thread-safe: one caller at a time (the router's step loop is
    single-threaded by design, and the worker serves one router).

    ``timeout`` (seconds) is applied to the raw fd via
    ``SO_RCVTIMEO``/``SO_SNDTIMEO`` — the native ``recvmsg`` loop then
    returns an error instead of blocking forever on a wedged peer,
    which surfaces here as :class:`RpcConnectionError` (the liveness
    signal).
    """

    def __init__(self, sock, timeout: Optional[float] = None,
                 codec=None):
        import socket as _socket

        self.sock = sock
        self.fd = sock.fileno()
        self.codec = span_codec_id(codec)
        self.alive = True
        # Byte accounting (the bench's RPC-tax / bytes-saved keys).
        self.msgs_sent = 0
        self.msgs_received = 0
        self.bytes_sent = 0
        self.bytes_received = 0
        self.span_raw_bytes = 0    # pre-codec payload bytes, both ways
        self.span_wire_bytes = 0   # on-the-wire span bytes, both ways
        # Distributed-tracing context (serve/trace.py): `trace_id` is
        # stamped on the NEXT outgoing frame's header and consumed;
        # `last_trace_id` is the most recent received frame's stamp
        # (0 = no context) — the worker's dispatch reads it to tag the
        # engine spans of the request the frame carried.
        self.trace_id = 0
        self.last_trace_id = 0
        if timeout is not None:
            self.set_timeout(timeout)

    def set_timeout(self, timeout: Optional[float]) -> None:
        """(Re)apply SO_RCVTIMEO/SO_SNDTIMEO on the raw fd — the
        native blocking syscalls honor these, unlike Python-level
        socket timeouts. None/0 = block forever."""
        import socket as _socket

        timeout = timeout or 0.0
        tv = struct.pack("<qq", int(timeout),
                         int((timeout % 1.0) * 1e6))
        self.sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVTIMEO, tv)
        self.sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDTIMEO, tv)

    # -- framing -----------------------------------------------------

    def send(self, obj) -> None:
        if not self.alive:
            raise RpcConnectionError("connection already closed")
        body: List[bytes] = []
        spans: List[Tuple[np.ndarray, int]] = []
        _pack_value(obj, body, spans, self.codec)
        trace_id, self.trace_id = self.trace_id, 0
        frame = struct.pack(
            "<IHHQ", RPC_MAGIC, RPC_PROTOCOL_VERSION, len(spans),
            trace_id & 0xFFFFFFFFFFFFFFFF) + b"".join(body)
        chunks = [struct.pack("<Q", len(frame)), frame]
        chunks += [p for p, _ in spans]
        bufs, lens, n, keep = _as_iovec(chunks)
        ok = get_lib().hvd_tcp_sendv(self.fd, bufs, lens, n)
        del keep
        if ok != 1:
            self._dead("send failed (peer gone?)")
        self.msgs_sent += 1
        self.bytes_sent += 8 + len(frame) + sum(p.nbytes for p, _ in spans)
        for p, raw in spans:
            self.span_wire_bytes += p.nbytes
            self.span_raw_bytes += raw

    def _recvv(self, chunks) -> None:
        bufs, lens, n, keep = _as_iovec(chunks)
        ok = get_lib().hvd_tcp_recvv(self.fd, bufs, lens, n)
        del keep
        if ok != 1:
            self._dead("recv failed (peer gone?)")

    def recv(self):
        if not self.alive:
            raise RpcConnectionError("connection already closed")
        hdr = bytearray(8)
        self._recvv([np.frombuffer(hdr, np.uint8)])
        (flen,) = struct.unpack("<Q", hdr)
        if not 8 <= flen <= MAX_FRAME_BYTES:
            self._dead(f"insane frame length {flen}")
        frame = np.empty(flen, np.uint8)
        self._recvv([frame])
        r = _Reader(frame.tobytes())
        magic, version, n_spans = r.take("<IHH")
        if magic != RPC_MAGIC:
            self.close()
            raise RpcProtocolError(
                f"bad frame magic {magic:#x} (expected {RPC_MAGIC:#x})")
        if version != RPC_PROTOCOL_VERSION:
            # Version check runs BEFORE the v2 trace_id field is
            # parsed: a v1 frame's header simply ends here, so skew is
            # a clean structured error naming both versions — never a
            # misparse of body bytes as a trace id.
            self.close()
            raise RpcProtocolError(
                f"peer speaks rpc protocol v{version}, this side "
                f"v{RPC_PROTOCOL_VERSION} — upgrade in lockstep")
        (self.last_trace_id,) = r.take("<Q")
        stubs: List[_ArrayStub] = []
        try:
            obj = _unpack_value(r, stubs)
        except struct.error as e:
            self.close()
            raise RpcProtocolError(f"corrupt frame body: {e}") from None
        except RpcProtocolError:
            # Unknown tag / bad span descriptor: the declared span
            # bytes were never drained, so the stream is desynced —
            # close rather than let the next recv parse span payload
            # as a length prefix.
            self.close()
            raise
        if len(stubs) != n_spans:
            self.close()
            raise RpcProtocolError(
                f"frame declares {n_spans} spans, body references "
                f"{len(stubs)}")
        if stubs:
            self._recvv([s.buf for s in stubs])
        lib = get_lib()
        obj = _resolve_stubs(obj, lib)
        self.msgs_received += 1
        self.bytes_received += 8 + flen + sum(s.wire_bytes for s in stubs)
        for s in stubs:
            self.span_wire_bytes += s.wire_bytes
            self.span_raw_bytes += (s.arr.nbytes if s.arr is not None
                                    else s.wire_bytes)
        return obj

    def _dead(self, why: str):
        self.close()
        raise RpcConnectionError(why)

    def close(self) -> None:
        if self.alive:
            self.alive = False
            try:
                self.sock.close()
            except OSError:
                pass

    # -- request/response --------------------------------------------

    def call_begin(self, method: str, *args, **kwargs) -> None:
        """Write one request frame WITHOUT waiting for the reply — the
        router's async step fan-out sends every busy worker's ``step``
        first, then collects. Must be paired with exactly one
        :meth:`call_finish` before any other call on this connection
        (the channel is strict request/response)."""
        self.send({"t": "call", "m": method, "a": list(args),
                   "k": kwargs})

    def call_finish(self):
        """Collect the reply of a :meth:`call_begin`. Remote
        exceptions of known types (ValueError, KeyError, the serve
        tier's structured rejections) re-raise natively; anything else
        raises :class:`RpcRemoteError`."""
        reply = self.recv()
        t = reply.get("t")
        if t == "ret":
            return reply.get("v")
        if t == "err":
            raise _rebuild_exception(reply)
        self.close()
        raise RpcProtocolError(f"unexpected reply type {t!r}")

    def call(self, method: str, *args, **kwargs):
        """One blocking RPC: ``call_begin`` + ``call_finish``."""
        self.call_begin(method, *args, **kwargs)
        return self.call_finish()


def _exception_to_wire(e: BaseException) -> Dict[str, Any]:
    fields = {}
    for f in ("reason", "queue_depth", "retry_after_s", "deadline_class",
              "http_status"):
        v = getattr(e, f, None)
        if isinstance(v, (int, float, str)) or v is None:
            if hasattr(e, f):
                fields[f] = v
    return {"t": "err", "e": type(e).__name__, "msg": str(e),
            "f": fields}


def _rebuild_exception(reply: Dict[str, Any]) -> BaseException:
    name = reply.get("e", "RuntimeError")
    msg = reply.get("msg", "")
    fields = reply.get("f") or {}
    if name == "ValueError":
        return ValueError(msg)
    if name == "KeyError":
        return KeyError(msg)
    if name == "TypeError":
        return TypeError(msg)
    if name in ("QueueFull", "FleetSaturated"):
        from horovod_tpu.serve.engine import QueueFull
        return QueueFull(msg, reason=fields.get("reason", "queue_full"),
                         queue_depth=int(fields.get("queue_depth") or 0),
                         retry_after_s=fields.get("retry_after_s"))
    if name == "OutOfBlocks":
        from horovod_tpu.serve.kv_cache import OutOfBlocks
        return OutOfBlocks(msg)
    return RpcRemoteError(name, msg, fields)


def serve_connection(conn: RpcConn, handlers: Dict[str, Any]) -> None:
    """Dispatch loop for the server side: read a call, run its
    handler, reply — until the peer disconnects or a handler named in
    ``handlers['__closing__']`` (e.g. ``shutdown``) has replied.
    Handler exceptions become structured error replies; the loop only
    exits on transport-level failure."""
    closing = set(handlers.get("__closing__", ()))
    while True:
        try:
            msg = conn.recv()
        except (RpcConnectionError, RpcProtocolError):
            return
        method = msg.get("m")
        fn = handlers.get(method)
        try:
            if fn is None:
                raise KeyError(f"unknown rpc method {method!r}")
            ret = fn(*(msg.get("a") or []), **(msg.get("k") or {}))
            reply = {"t": "ret", "v": ret}
        except RpcConnectionError:
            return
        except Exception as e:   # noqa: BLE001 — becomes a wire error
            reply = _exception_to_wire(e)
        try:
            conn.send(reply)
        except (RpcConnectionError, RpcProtocolError):
            return
        if method in closing:
            conn.close()
            return


# ---------------------------------------------------------------------------
# Worker lifecycle
# ---------------------------------------------------------------------------

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

#: Stdout announce line prefix the worker prints once it listens.
WORKER_READY_PREFIX = "HVD-SERVE-WORKER ready"


@dataclasses.dataclass
class WorkerHandle:
    """A spawned (or attached) serve worker: its RPC connection plus,
    for spawned workers, the process handle for kill/cleanup.
    ``host`` is where the worker's sockets live — peers dial its bulk
    migration listener at ``(host, peer_port-from-configure)``."""

    conn: RpcConn
    proc: Optional[subprocess.Popen] = None
    port: int = 0
    host: str = "127.0.0.1"

    def kill(self) -> None:
        """Hard-kill the worker (the failover tests' crash lever)."""
        self.conn.close()
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=30)

    def close(self) -> None:
        """Best-effort graceful stop: shutdown RPC, then reap."""
        if self.conn.alive:
            try:
                self.conn.call("shutdown")
            except RpcError:
                pass
            self.conn.close()
        if self.proc is not None:
            try:
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=30)


def spawn_worker(*, env: Optional[Dict[str, str]] = None,
                 start_timeout: float = 120.0,
                 rpc_timeout: Optional[float] = 300.0,
                 codec=None, via_bin: bool = False) -> WorkerHandle:
    """Launch ``python -m horovod_tpu.serve.worker`` on this host
    (``via_bin=True`` execs the ``bin/hvd-serve-worker`` console entry
    instead — same worker, the spelling a remote host would run), wait
    for its listen announce, connect, and return the handle. The child
    inherits the environment (so ``JAX_PLATFORMS`` etc. apply) with
    the repo root prepended to ``PYTHONPATH``."""
    import socket

    child_env = dict(os.environ)
    if env:
        child_env.update(env)
    child_env["PYTHONPATH"] = os.pathsep.join(
        [_REPO_ROOT] + ([child_env["PYTHONPATH"]]
                        if child_env.get("PYTHONPATH") else []))
    child_env.setdefault("PYTHONUNBUFFERED", "1")
    cmd = ([sys.executable, os.path.join(_REPO_ROOT, "bin",
                                         "hvd-serve-worker")]
           if via_bin else
           [sys.executable, "-m", "horovod_tpu.serve.worker"])
    proc = subprocess.Popen(
        cmd + ["--port", "0"],
        stdout=subprocess.PIPE, text=True, env=child_env)
    import select

    port = None
    deadline = time.monotonic() + start_timeout
    while time.monotonic() < deadline:
        # select-gate the readline: a child that wedges SILENTLY
        # (alive, no output) must still honor start_timeout instead
        # of blocking this process on the pipe forever.
        ready, _, _ = select.select([proc.stdout], [], [],
                                    max(0.0, deadline - time.monotonic()))
        if not ready:
            break
        line = proc.stdout.readline()
        if not line:
            proc.kill()
            raise RpcConnectionError(
                f"serve worker exited during startup "
                f"(rc={proc.poll()})")
        if line.startswith(WORKER_READY_PREFIX):
            port = int(line.split("port=")[1].split()[0])
            break
    if port is None:
        proc.kill()
        raise RpcConnectionError(
            f"serve worker did not announce within {start_timeout}s")
    # Keep draining the child's stdout so a chatty jax can never fill
    # the pipe and wedge the worker mid-step.
    threading.Thread(target=_drain, args=(proc.stdout,),
                     daemon=True).start()
    sock = socket.create_connection(("127.0.0.1", port),
                                    timeout=start_timeout)
    sock.settimeout(None)   # native syscalls need a BLOCKING fd
    return WorkerHandle(conn=RpcConn(sock, timeout=rpc_timeout,
                                     codec=codec),
                        proc=proc, port=port)


def _drain(stream) -> None:
    try:
        for _ in stream:
            pass
    except (OSError, ValueError):
        pass


def connect_worker(host: str, port: int, *,
                   rpc_timeout: Optional[float] = 300.0,
                   codec=None) -> WorkerHandle:
    """Attach to an externally-launched worker (e.g. another host
    running ``bin/hvd-serve-worker``)."""
    import socket

    sock = socket.create_connection((host, port), timeout=rpc_timeout)
    sock.settimeout(None)
    return WorkerHandle(conn=RpcConn(sock, timeout=rpc_timeout,
                                     codec=codec), port=port, host=host)


# ---------------------------------------------------------------------------
# Config marshalling (router-side spelling of the worker's configure)
# ---------------------------------------------------------------------------

def model_cfg_to_wire(model_cfg) -> Dict[str, Any]:
    d = dataclasses.asdict(model_cfg)
    d["dtype"] = np.dtype(model_cfg.dtype).name
    return d


def serve_cfg_to_wire(serve_cfg) -> Dict[str, Any]:
    d = dataclasses.asdict(serve_cfg)
    d["cache_dtype"] = (None if serve_cfg.cache_dtype is None
                        else np.dtype(serve_cfg.cache_dtype).name)
    comp = serve_cfg.compression
    d["compression"] = (None if comp is None
                        else getattr(comp, "in_jit_codec", str(comp)))
    for k in ("batch_buckets", "prefill_buckets"):
        if d[k] is not None:
            d[k] = list(d[k])
    # Speculative sub-config: asdict() recursed into it with raw jnp
    # dtype objects the value codec can't ship — rebuild it in wire
    # shape (the draft model config marshals exactly like the target's).
    draft = serve_cfg.draft
    d["draft"] = (None if draft is None else {
        "model_cfg": model_cfg_to_wire(draft.model_cfg),
        "seed": int(draft.seed),
        "cache_dtype": (None if draft.cache_dtype is None
                        else np.dtype(draft.cache_dtype).name),
    })
    return d


def result_from_wire(d: Dict[str, Any], now: float):
    """Rebuild a RequestResult shipped as ages-relative-to-worker-now
    onto THIS process's clock (perf_counter epochs differ across
    processes; uniform re-anchoring preserves every latency delta)."""
    from horovod_tpu.serve.engine import RequestResult

    def at(age):
        return None if age is None else now - age

    return RequestResult(
        rid=int(d["rid"]), status=d["status"],
        http_status=int(d["http_status"]),
        tokens=[int(t) for t in d["tokens"]],
        n_prompt=int(d["n_prompt"]),
        submitted_at=at(d["age_submitted"]),
        first_token_at=at(d["age_first_token"]),
        finished_at=at(d["age_finished"]),
        reason=d["reason"], deadline_class=int(d["deadline_class"]),
        retry_after_s=d["retry_after_s"])


def handoff_from_wire(d: Dict[str, Any], now: float):
    from horovod_tpu.serve.engine import PrefillHandoff

    return PrefillHandoff(
        prompt=[int(t) for t in d["prompt"]],
        max_new=int(d["max_new"]),
        generated=[int(t) for t in d["generated"]],
        submitted_at=now - d["age_submitted"],
        first_token_at=now - d["age_first_token"],
        deadline_class=int(d["deadline_class"]),
        chain=[bytes(c) for c in d["chain"]],
        k_pages=d["k_pages"], v_pages=d["v_pages"],
        block_size=int(d["block_size"]),
        n_cached=int(d["n_cached"]),
        trace_id=int(d.get("trace_id") or 0))


def handoff_to_wire(h, now: float) -> Dict[str, Any]:
    return {
        "prompt": list(h.prompt), "max_new": h.max_new,
        "generated": list(h.generated),
        "age_submitted": now - h.submitted_at,
        "age_first_token": now - h.first_token_at,
        "deadline_class": h.deadline_class,
        "chain": list(h.chain),
        "k_pages": np.asarray(h.k_pages),
        "v_pages": np.asarray(h.v_pages),
        "block_size": h.block_size, "n_cached": h.n_cached,
        "trace_id": h.trace_id,
    }


def handoff_meta_to_wire(h, now: float) -> Dict[str, Any]:
    """The manifest half of a handoff — everything but the pages —
    for the direct-migration ``peer_begin`` frame. The pages follow as
    ``peer_chunk`` spans, so the target can reserve blocks (and fail
    fast on no-capacity) before a single bulk byte moves."""
    return {
        "prompt": list(h.prompt), "max_new": h.max_new,
        "generated": list(h.generated),
        "age_submitted": now - h.submitted_at,
        "age_first_token": now - h.first_token_at,
        "deadline_class": h.deadline_class,
        "chain": list(h.chain),
        "block_size": h.block_size, "n_cached": h.n_cached,
        "n_pages": h.n_pages,
        "trace_id": h.trace_id,
    }


def handoff_meta_from_wire(d: Dict[str, Any], now: float) -> Dict[str, Any]:
    """Inverse of :func:`handoff_meta_to_wire`, re-anchored onto this
    process's clock — the dict ``ServeEngine.inject_begin`` takes."""
    return {
        "prompt": [int(t) for t in d["prompt"]],
        "max_new": int(d["max_new"]),
        "generated": [int(t) for t in d["generated"]],
        "submitted_at": now - d["age_submitted"],
        "first_token_at": now - d["age_first_token"],
        "deadline_class": int(d["deadline_class"]),
        "chain": [bytes(c) for c in d["chain"]],
        "block_size": int(d["block_size"]),
        "n_cached": int(d["n_cached"]),
        "n_pages": int(d["n_pages"]),
        "trace_id": int(d.get("trace_id") or 0),
    }


# ---------------------------------------------------------------------------
# RemoteReplica: the engine seam over a connection
# ---------------------------------------------------------------------------

class _RemoteAllocatorView:
    """The slice of ``BlockAllocator`` the router reads, backed by the
    worker's configure reply and the freshest admission snapshot (the
    router always snapshots before it checks capacity, so the cached
    ``kv_blocks_free`` is current within one placement decision —
    exactly the in-process read pattern)."""

    def __init__(self, n_blocks: int, block_size: int):
        self.n_blocks = n_blocks
        self.block_size = block_size
        self._free = n_blocks - 1

    def blocks_for_tokens(self, n_tokens: int) -> int:
        return -(-n_tokens // self.block_size)

    def can_alloc(self, n: int) -> bool:
        return n <= self._free

    @property
    def n_free(self) -> int:
        return self._free


class RemoteReplicaMetrics:
    """Router-process view of a worker's ``ServeMetrics``: the
    heartbeat payload keeps a cached snapshot plus the delta-shipped
    latency samples, and registers with the shared Prometheus
    exposition so ONE scrape of the router process covers every worker
    process too (same ``serve_*{instance=...}`` series a local replica
    would emit)."""

    def __init__(self, instance: str):
        self.instance = instance
        self.first_token_s: List[float] = []
        self.per_token_s: List[float] = []
        self._snap: Dict[str, Any] = {}
        from horovod_tpu.metrics import register_exporter_weak
        register_exporter_weak(f"serve_remote_{id(self)}", self,
                               "prometheus")

    def update(self, snap: Dict[str, Any], first_token_s, per_token_s):
        from horovod_tpu.serve.metrics import MAX_SAMPLES
        self._snap = snap
        for dst, new in ((self.first_token_s, first_token_s),
                         (self.per_token_s, per_token_s)):
            room = MAX_SAMPLES - len(dst)
            if room > 0:
                dst.extend(float(x) for x in new[:room])

    def snapshot(self) -> Dict[str, Any]:
        return dict(self._snap)

    def prometheus(self) -> str:
        from horovod_tpu.metrics import render_gauges
        return render_gauges("serve", self.snapshot(),
                             labels={"instance": self.instance})


class RemoteReplica:
    """One serve worker process behind the engine seam. The router
    treats this object exactly like a ``ServeEngine`` — same methods,
    same semantics — with three RPC-shaped differences:

    * results/latency samples arrive batched on the ``step`` /
      ``heartbeat`` replies (one round trip per iteration, not one per
      request) and are re-anchored onto the router's clock;
    * ``metrics``/``allocator`` are cached views refreshed by those
      replies (the router always snapshots before acting, so the cache
      is current within a decision);
    * any transport failure raises :class:`RpcConnectionError`, the
      router's dead-worker signal.
    """

    remote = True

    def __init__(self, handle: WorkerHandle, model_cfg, serve_cfg, *,
                 seed: int, instance: str, clock=time.perf_counter,
                 trace=None):
        self._handle = handle
        self._conn = handle.conn
        self._clock = clock
        self.instance = instance
        # Router-side trace recorder (serve/trace.RouterTrace, None =
        # tracing off): placement RPCs record their wire time under
        # the request's trace id.
        self._trace = trace
        # Worker-clock offset estimation (docs/observability.md
        # "One timebase"): every heartbeat reply carries the worker's
        # `now`; this side brackets the RPC with t0/t1 and estimates
        # offset = worker_now - (t0+t1)/2 — the RTT-midpoint re-anchor
        # of the PR 11 age discipline, made persistent. The sample
        # with the smallest RTT seen so far wins (its midpoint bound
        # is tightest), so the estimate survives heartbeat gaps and
        # only ever improves.
        self.clock_offset = 0.0       # worker clock - router clock
        self.clock_rtt = float("inf")  # RTT of the winning sample
        ret = self._conn.call(
            "configure", model_cfg=model_cfg_to_wire(model_cfg),
            serve_cfg=serve_cfg_to_wire(serve_cfg), seed=int(seed),
            instance=instance, kv_codec=self._conn.codec)
        self.allocator = _RemoteAllocatorView(int(ret["n_blocks"]),
                                              int(ret["block_size"]))
        # Direct-migration dial target: the worker's bulk peer
        # listener (docs/serving.md "Direct migration"). 0 = the
        # worker has none; the router then stays on the relayed path.
        self.peer_host = handle.host
        self.peer_port = int(ret.get("peer_port") or 0)
        self.metrics = RemoteReplicaMetrics(instance)
        self._results: Dict[int, Any] = {}
        self._pending = False
        self.last_beat = -float("inf")
        self._absorb_beat(ret["beat"])

    # -- beat plumbing ----------------------------------------------

    def _absorb_beat(self, beat: Dict[str, Any],
                     t0: Optional[float] = None,
                     t1: Optional[float] = None) -> None:
        now = self._clock()
        self._pending = bool(beat["pending"])
        self.allocator._free = int(beat["kv_blocks_free"])
        self.metrics.update(beat["snap"], beat["ft"], beat["pt"])
        for erid, rd in beat["results"].items():
            self._results[int(erid)] = result_from_wire(rd, now)
        self.last_beat = now
        # Offset sample: only from calls the caller bracketed (the
        # cheap symmetric heartbeat — a step RPC's reply time includes
        # the worker's compute, which would skew the midpoint).
        if (t0 is not None and t1 is not None
                and beat.get("now") is not None):
            rtt = t1 - t0
            if rtt < self.clock_rtt:
                self.clock_rtt = rtt
                self.clock_offset = (float(beat["now"])
                                     - (t0 + t1) / 2.0)

    def heartbeat(self) -> None:
        """Liveness probe + metrics scrape + clock-offset sample in
        one round trip; raises :class:`RpcConnectionError` when the
        worker is gone."""
        t0 = self._clock()
        beat = self._conn.call("heartbeat")
        self._absorb_beat(beat, t0, self._clock())

    # -- the engine seam ---------------------------------------------

    @property
    def pending(self) -> bool:
        return self._pending

    def admission_snapshot(self) -> Dict[str, float]:
        snap = self._conn.call("admission_snapshot")
        self.allocator._free = int(snap["kv_blocks_free"])
        return snap

    def cached_chain_len(self, chain: Sequence[bytes]) -> int:
        if not chain:
            return 0
        return int(self._conn.call("cached_chain_len", list(chain)))

    def submit(self, prompt, max_new_tokens=None, deadline=None,
               deadline_class: int = 0, prefill_only: bool = False,
               chain=None, trace_id: int = 0) -> int:
        # Absolute deadlines are ROUTER-clock times; processes don't
        # share a perf_counter epoch, so the wire carries the time
        # REMAINING and the worker re-anchors onto its own clock.
        deadline_in = (None if deadline is None
                       else deadline - self._clock())
        # The trace id rides the NEXT frame's v2 header (not the
        # payload): the worker's dispatch reads it off the conn, so
        # every placement verb propagates identity the same way.
        self._conn.trace_id = trace_id
        t0 = self._clock()
        erid = self._conn.call(
            "submit", prompt=[int(t) for t in prompt],
            max_new_tokens=max_new_tokens, deadline_in=deadline_in,
            deadline_class=deadline_class, prefill_only=prefill_only,
            chain=list(chain) if chain is not None else None)
        if trace_id and self._trace is not None:
            self._trace.span("rpc:submit", t0, self._clock() - t0,
                             trace=trace_id, instance=self.instance)
        self._pending = True
        return int(erid)

    def withdraw(self, rid: int) -> bool:
        return bool(self._conn.call("withdraw", int(rid)))

    def step(self) -> None:
        self._absorb_beat(self._conn.call("step"))

    # -- async step fan-out (router._step_replicas) ------------------

    def step_begin(self) -> bool:
        """Fire the step request frame and return immediately; the
        worker computes its iteration while the router steps other
        replicas. MUST be paired with :meth:`step_finish` (and is —
        the router pairs them within one `_step_replicas`). Returns
        True so the router's `_guard` can distinguish success from a
        detected death."""
        self._conn.call_begin("step")
        return True

    def step_finish(self) -> None:
        """Collect and apply a :meth:`step_begin`'s beat reply."""
        self._absorb_beat(self._conn.call_finish())

    def result(self, rid: int):
        return self._results.get(rid)

    def handoff_ready(self) -> List[int]:
        return [int(r) for r in self._conn.call("handoff_ready")]

    def export_prefilled(self, rid: int):
        d = self._conn.call("export_prefilled", int(rid))
        return handoff_from_wire(d, self._clock())

    def inject_prefilled(self, h) -> int:
        # Tag the frame too: the handoff payload carries trace_id for
        # the engine, the header keeps the wire-level convention
        # uniform across placement verbs.
        self._conn.trace_id = getattr(h, "trace_id", 0)
        erid = self._conn.call("inject_prefilled",
                               handoff_to_wire(h, self._clock()))
        self._pending = True
        return int(erid)

    def export_trace(self) -> Dict[str, Any]:
        """This worker's chrome-trace events + timebase anchor, with
        the router's RTT-estimated clock offset stamped in (the merge
        key ``bin/hvd-trace`` uses to re-anchor worker spans onto the
        router clock)."""
        d = self._conn.call("export_trace")
        d["meta"]["instance"] = self.instance
        d["meta"]["clock_offset"] = self.clock_offset
        d["meta"]["clock_rtt"] = (None if self.clock_rtt == float("inf")
                                  else self.clock_rtt)
        return d

    def running_exportable(self) -> List[int]:
        return [int(r) for r in self._conn.call("running_exportable")]

    def export_running(self, rid: int):
        d = self._conn.call("export_running", int(rid))
        return handoff_from_wire(d, self._clock())

    # -- direct migration (docs/serving.md "Direct migration") -------

    def migrate_direct(self, erid: int, kind: str, host: str,
                       port: int, chunk_pages: int,
                       epoch: int) -> Dict[str, Any]:
        """Ask THIS worker (the source) to stream sequence ``erid``'s
        pages point-to-point to a peer worker's bulk listener — the
        control frame of the direct plane; the router never touches
        the pages. Returns the worker's status dict: ``ok`` (with the
        target-side erid and byte/latency accounting),
        ``dial_failed`` (sequence untouched — fall back to relayed),
        or ``failed`` (exported then lost — requeue the request)."""
        return self._conn.call(
            "migrate_to", kind=str(kind), erid=int(erid),
            host=str(host), port=int(port),
            chunk_pages=int(chunk_pages), epoch=int(epoch))

    def note_remote_inject(self) -> None:
        """A sequence landed on this worker OUTSIDE the router's
        connection (a peer-streamed inject): mark the cached pending
        flag so the step loop drives the worker before the next beat
        refreshes it."""
        self._pending = True

    # -- lifecycle ---------------------------------------------------

    @property
    def alive(self) -> bool:
        return self._conn.alive

    def mark_dead(self) -> None:
        self._conn.close()

    def shutdown(self) -> None:
        self._handle.close()
