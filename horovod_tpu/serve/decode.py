"""jit'd prefill + decode step functions over the paged KV cache.

Two compiled programs drive all serving traffic:

* :func:`prefill` — run one prompt (padded to a length bucket) through
  the transformer, write its K/V into the sequence's cache blocks, and
  emit the first generated token from the last real position's logits.
* :func:`prefill_resume` — the preemptible/suffix variant: run a
  *chunk* of a prompt starting at a block-aligned token ``offset``,
  attending over the pages already present in the sequence's blocks
  (a prefix mapped in from the content-addressed cache, or earlier
  chunks of the same prompt) and writing the chunk's new pages through
  the block table. The chunk length is a new jit bucket dimension;
  ``offset`` stays traced. This is what makes prefix-cache hits pay
  only suffix FLOPs and lets the engine interleave long prefills with
  decode iterations (chunked prefill).
* :func:`decode` — one iteration-level step for the whole running
  batch (padded to a batch bucket): embed each sequence's last token,
  append its K/V at the sequence's current position through the block
  table, attend against the gathered pages, and emit the next token
  per sequence.

Both are shape-bucketed (see ``kv_cache.pick_bucket``) so the jit
cache holds a handful of programs total — batch membership, sequence
lengths, and block placement all change per step without recompiling.

Sharding: params arrive sharded by ``models.transformer.param_specs``
(tp on heads/FFN-hidden, fsdp on the other matrix dim), the KV pool is
tp-sharded on the KV-head dim (``kv_cache.init_kv_cache``), and GSPMD
propagates — the attention-out and FFN-down matmuls end in the same
in-jit tp ``psum`` pair as the training forward, so tensor-parallel
decode exercises :mod:`horovod_tpu.ops.collectives`' data plane on the
hot loop (the EQuARX property: collectives stay inside the XLA
program, on ICI).

Numerics match ``models.transformer`` deliberately: reused
``_rmsnorm``/``embed_lookup``, the same unfused q/k/v/gate/up
projections, f32 softmax and silu, ``local_attention``'s einsum
order — so incremental decode tracks the full-context forward to
float tolerance, and served decode is bit-identical to single-request
decode (same programs, row-independent math).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.models import moe as moe_lib
from horovod_tpu.models import transformer as tf_lib
from horovod_tpu.parallel.ring_attention import local_attention
from horovod_tpu.serve.kv_cache import NULL_BLOCK

_NEG_BIG = -1e30  # matches ring_attention's finite "-inf"


def _rope_at(x, pos, theta):
    """Rotary embedding at explicit per-(batch, seq) positions.

    ``x``: [B, T, H, D]; ``pos``: [B, T] int32. Unlike the training
    forward's ``_rope`` (one shared position vector), every batch row
    carries its own positions — in a decode batch each sequence is at
    a different length.
    """
    d = x.shape[-1]
    inv = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = pos[..., None].astype(jnp.float32) * inv          # [B, T, D/2]
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    y1 = x1 * cos - x2 * sin
    y2 = x2 * cos + x1 * sin
    return jnp.stack([y1, y2], axis=-1).reshape(x.shape).astype(x.dtype)


def _qkv(cfg, lp, x, pos):
    """Pre-norm + q/k/v projections + rope (same unfused matmuls and
    dtype discipline as ``decoder_layer``). k/v keep Hkv heads — the
    cache stores pre-GQA-repeat, post-rope K/V."""
    H, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    B, T = x.shape[0], x.shape[1]
    h = tf_lib._rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"]).reshape(B, T, H, Dh)
    k = (h @ lp["wk"]).reshape(B, T, Hkv, Dh)
    v = (h @ lp["wv"]).reshape(B, T, Hkv, Dh)
    return (_rope_at(q, pos, cfg.rope_theta),
            _rope_at(k, pos, cfg.rope_theta), v)


def _ffn(cfg, lp, x):
    """Post-attention FFN block, decoder_layer's exact math. MoE
    configs take the GSPMD :func:`moe_lib.moe_ffn` (experts stay
    ep-sharded by the weight specs; the quantized-dispatch island is a
    training-path construct — decode's T=1 slabs are too narrow to pay
    for restructuring, see docs/serving.md). The aux loss is routing
    telemetry only at serve time and is dropped."""
    h = tf_lib._rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    if cfg.moe is not None:
        y, _aux = moe_lib.moe_ffn(h, lp["moe"], cfg.moe)
        return x + y.astype(cfg.dtype)
    g = jax.nn.silu((h @ lp["w_gate"]).astype(jnp.float32))
    u = (h @ lp["w_up"]).astype(jnp.float32)
    return x + ((g * u).astype(cfg.dtype) @ lp["w_down"]).astype(cfg.dtype)


def make_serve_fns(cfg, mesh: Optional[Any] = None, *, block_size: int,
                   table_width: int, compression=None):
    """Build (prefill, prefill_resume, decode, inject, verify) jitted
    closures for ``cfg`` over ``mesh``. ``table_width`` is the static
    block-table row length (blocks per sequence, worst case); caches
    are donated so steady-state decode — and the handoff-page
    ``inject`` scatter — update the pool in place. ``verify`` is the
    speculative-decoding chunk step (one target pass over k proposed
    tokens; see serve/speculative.py).

    ``compression`` (a ``hvd.Compression`` member; None = uncompressed,
    bitwise the pre-existing programs) is the serving face of the same
    knob the training planes read: it narrows the embed table's mesh
    movement in every prefill/decode program (see
    ``transformer.embed_lookup``) — the per-step table reshard is the
    one table-sized transfer on the decode hot loop when the vocab-
    parallel island can't run.

    Memoized: engines sharing (cfg, mesh, block geometry, compression)
    — e.g. the benchmark's continuous and static schedulers, or a
    fleet of per-tenant engines — reuse one pair of jit closures and
    therefore one compiled program per shape bucket."""
    return _cached_serve_fns(cfg, mesh, block_size, table_width,
                             compression)


@functools.lru_cache(maxsize=64)
def _cached_serve_fns(cfg, mesh, block_size: int, table_width: int,
                      compression=None):
    H, Hkv, Dh = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    rep = H // Hkv
    scale = Dh ** -0.5

    def prefill(params, kc, vc, tokens, length, block_table):
        """tokens [Tp] (bucket-padded), length scalar i32 (real prompt
        length), block_table [table_width] i32. Returns (kc, vc,
        first_token)."""
        Tp = tokens.shape[0]
        n_blk = Tp // block_size
        assert n_blk <= table_width, (
            f"prompt bucket {Tp} needs {n_blk} blocks > table width "
            f"{table_width}")
        x = tf_lib.embed_lookup(params["embed"], tokens[None], cfg.dtype,
                                mesh, compression)             # [1, Tp, D]
        pos = jnp.arange(Tp, dtype=jnp.int32)[None]            # [1, Tp]

        def body(x, per_layer):
            lp, kc_l, vc_l = per_layer
            q, k, v = _qkv(cfg, lp, x, pos)
            # Pages: the padded prompt is block-aligned, so the write
            # is a plain blockwise scatter. Bucket blocks past the
            # allocation land on the null block (id 0) — written
            # garbage there is never read (attention masks by length).
            kc_l = kc_l.at[block_table[:n_blk]].set(
                k[0].reshape(n_blk, block_size, Hkv, Dh).astype(kc_l.dtype))
            vc_l = vc_l.at[block_table[:n_blk]].set(
                v[0].reshape(n_blk, block_size, Hkv, Dh).astype(vc_l.dtype))
            kk, vv = k, v
            if rep > 1:
                kk = jnp.repeat(kk, rep, axis=2)
                vv = jnp.repeat(vv, rep, axis=2)
            o = local_attention(q, kk, vv, causal=True)
            x = x + (o.reshape(1, Tp, H * Dh) @ lp["wo"]).astype(cfg.dtype)
            x = _ffn(cfg, lp, x)
            return x, (kc_l, vc_l)

        x, (kc, vc) = lax.scan(body, x, (params["layers"], kc, vc))
        x = tf_lib._rmsnorm(x, params["final_norm"], cfg.norm_eps)
        x_last = jnp.take(x[0], length - 1, axis=0)            # [D]
        logits = (x_last @ params["lm_head"]).astype(jnp.float32)
        return kc, vc, jnp.argmax(logits).astype(jnp.int32)

    def prefill_resume(params, kc, vc, tokens, offset, length, block_table):
        """One prefill *chunk* starting at block-aligned token
        ``offset``. tokens [Tc] (chunk bucket-padded), offset scalar
        i32 (tokens already in the cache for this sequence: a mapped
        prefix-cache hit and/or earlier chunks), length scalar i32
        (real tokens in this chunk), block_table [table_width] i32.

        Queries attend over ALL pages gathered through the table
        (prefix pages written by whoever computed them + this chunk's
        own pages, scattered first) under a global-position causal
        mask, so the math per real token is position-dependent only —
        identical whether the prefix was computed here, by an earlier
        chunk, or by another sequence entirely (the bitwise
        cache-on/off parity property).

        Returns (kc, vc, tok) where tok is the argmax at the chunk's
        last real position — the sequence's first generated token when
        this is the final chunk; callers ignore it for earlier chunks
        (it reads mid-prompt logits then).
        """
        Tc = tokens.shape[0]
        n_blk = Tc // block_size
        S = table_width * block_size
        x = tf_lib.embed_lookup(params["embed"], tokens[None], cfg.dtype,
                                mesh, compression)             # [1, Tc, D]
        pos = offset + jnp.arange(Tc, dtype=jnp.int32)[None]   # [1, Tc]
        # Chunk rows land in table slots off_blk..off_blk+n_blk. Rows
        # whose slot falls past the table (bucket padding of the last
        # chunk at high offsets) are routed to the null block — same
        # never-read garbage contract as the monolithic prefill's
        # padding blocks. A plain dynamic_slice would CLAMP the start
        # instead and overwrite real prefix pages.
        slot = offset // block_size + jnp.arange(n_blk, dtype=jnp.int32)
        blks = jnp.where(
            slot < table_width,
            jnp.take(block_table, jnp.minimum(slot, table_width - 1)),
            NULL_BLOCK)

        def body(x, per_layer):
            lp, kc_l, vc_l = per_layer
            q, k, v = _qkv(cfg, lp, x, pos)
            kc_l = kc_l.at[blks].set(
                k[0].reshape(n_blk, block_size, Hkv, Dh).astype(kc_l.dtype))
            vc_l = vc_l.at[blks].set(
                v[0].reshape(n_blk, block_size, Hkv, Dh).astype(vc_l.dtype))
            # Gather every page of this sequence (its table; unused
            # entries hold the null block) and mask by global position:
            # key j visible to query at global position p iff j <= p.
            # All such keys are real — the prefix was written before
            # this chunk ran, the chunk's own keys one line up.
            kp = kc_l[block_table].reshape(1, S, Hkv, Dh).astype(q.dtype)
            vp = vc_l[block_table].reshape(1, S, Hkv, Dh).astype(q.dtype)
            if rep > 1:
                kp = jnp.repeat(kp, rep, axis=2)
                vp = jnp.repeat(vp, rep, axis=2)
            s = jnp.einsum("bqhd,bkhd->bhqk", q, kp,
                           preferred_element_type=jnp.float32) * scale
            kpos = jnp.arange(S, dtype=jnp.int32)
            mask = kpos[None, :] <= pos[0][:, None]            # [Tc, S]
            s = jnp.where(mask[None, None], s, _NEG_BIG)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vp.dtype), vp,
                           preferred_element_type=jnp.float32).astype(q.dtype)
            x = x + (o.reshape(1, Tc, H * Dh) @ lp["wo"]).astype(cfg.dtype)
            x = _ffn(cfg, lp, x)
            return x, (kc_l, vc_l)

        x, (kc, vc) = lax.scan(body, x, (params["layers"], kc, vc))
        x = tf_lib._rmsnorm(x, params["final_norm"], cfg.norm_eps)
        x_last = jnp.take(x[0], length - 1, axis=0)            # [D]
        logits = (x_last @ params["lm_head"]).astype(jnp.float32)
        return kc, vc, jnp.argmax(logits).astype(jnp.int32)

    def decode(params, kc, vc, tokens, positions, block_tables):
        """One continuous-batching step. tokens [B] (each sequence's
        last token), positions [B] (its current cache length — where
        the token's K/V lands), block_tables [B, table_width]. Padded
        batch slots carry token 0 / position 0 / an all-null table;
        their lane writes and reads only touch the null block and
        their outputs are discarded by the engine. Returns (kc, vc,
        next_tokens [B])."""
        B = tokens.shape[0]
        S = table_width * block_size
        x = tf_lib.embed_lookup(params["embed"], tokens[:, None], cfg.dtype,
                                mesh, compression)             # [B, 1, D]
        pos = positions[:, None]

        def body(x, per_layer):
            lp, kc_l, vc_l = per_layer
            q, k, v = _qkv(cfg, lp, x, pos)
            # Positions past the table (a speculative draft's proposal
            # frontier near a sequence's cap) route to the null block.
            # The unguarded take_along_axis would CLAMP the slot and
            # overwrite the sequence's last real block instead.
            slot = positions // block_size                     # [B]
            blk = jnp.take_along_axis(
                block_tables,
                jnp.minimum(slot, table_width - 1)[:, None], axis=1)[:, 0]
            blk = jnp.where(slot < table_width, blk, NULL_BLOCK)
            phys = blk * block_size + positions % block_size   # [B]
            flat = (-1, Hkv, Dh)
            kc_l = kc_l.reshape(flat).at[phys].set(
                k[:, 0].astype(kc_l.dtype)).reshape(kc_l.shape)
            vc_l = vc_l.reshape(flat).at[phys].set(
                v[:, 0].astype(vc_l.dtype)).reshape(vc_l.shape)
            # Gather this batch's pages through the block tables:
            # [B, W, bs, Hkv, Dh] -> [B, S, Hkv, Dh].
            kp = kc_l[block_tables].reshape(B, S, Hkv, Dh).astype(q.dtype)
            vp = vc_l[block_tables].reshape(B, S, Hkv, Dh).astype(q.dtype)
            if rep > 1:
                kp = jnp.repeat(kp, rep, axis=2)
                vp = jnp.repeat(vp, rep, axis=2)
            s = jnp.einsum("bqhd,bkhd->bhqk", q, kp,
                           preferred_element_type=jnp.float32) * scale
            mask = jnp.arange(S, dtype=jnp.int32)[None] <= positions[:, None]
            s = jnp.where(mask[:, None, None, :], s, _NEG_BIG)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vp.dtype), vp,
                           preferred_element_type=jnp.float32).astype(q.dtype)
            x = x + (o.reshape(B, 1, H * Dh) @ lp["wo"]).astype(cfg.dtype)
            x = _ffn(cfg, lp, x)
            return x, (kc_l, vc_l)

        x, (kc, vc) = lax.scan(body, x, (params["layers"], kc, vc))
        x = tf_lib._rmsnorm(x, params["final_norm"], cfg.norm_eps)
        logits = (x[:, 0] @ params["lm_head"]).astype(jnp.float32)
        return kc, vc, jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def verify(params, kc, vc, tokens, positions, block_tables):
        """Speculative verification (see serve/speculative.py): one
        chunked target step over the batch's already-reserved pages.
        tokens [B, C] — per sequence ``[last_token, d1..d_{C-1}]``;
        positions [B] — each sequence's cache length (where the
        chunk's first K/V lands); block_tables [B, table_width].

        This is ``prefill_resume``'s math batched over sequences with
        ``decode``'s token-granularity page addressing (speculative
        chunks start mid-block): scatter the chunk's K/V through the
        table at per-token physical slots, gather ALL of each
        sequence's pages, attend under the global-position causal
        mask. The argmax at chunk position j is therefore bitwise what
        a plain decode step would emit after consuming
        ``tokens[:, :j+1]`` — the property greedy acceptance needs.
        Chunk positions past the table (proposal frontier near the
        cap) and padded batch rows route to the null block; their
        outputs are compared then discarded host-side (acceptance
        truncates at max_new before any such position can be
        emitted). Returns (kc, vc, out [B, C])."""
        B, C = tokens.shape
        S = table_width * block_size
        x = tf_lib.embed_lookup(params["embed"], tokens, cfg.dtype,
                                mesh, compression)             # [B, C, D]
        pos = positions[:, None] + jnp.arange(C, dtype=jnp.int32)[None]

        def body(x, per_layer):
            lp, kc_l, vc_l = per_layer
            q, k, v = _qkv(cfg, lp, x, pos)
            slot = pos // block_size                           # [B, C]
            blk = jnp.take_along_axis(
                block_tables, jnp.minimum(slot, table_width - 1), axis=1)
            blk = jnp.where(slot < table_width, blk, NULL_BLOCK)
            phys = (blk * block_size + pos % block_size).reshape(-1)
            flat = (-1, Hkv, Dh)
            kc_l = kc_l.reshape(flat).at[phys].set(
                k.reshape(-1, Hkv, Dh).astype(kc_l.dtype)).reshape(
                    kc_l.shape)
            vc_l = vc_l.reshape(flat).at[phys].set(
                v.reshape(-1, Hkv, Dh).astype(vc_l.dtype)).reshape(
                    vc_l.shape)
            kp = kc_l[block_tables].reshape(B, S, Hkv, Dh).astype(q.dtype)
            vp = vc_l[block_tables].reshape(B, S, Hkv, Dh).astype(q.dtype)
            if rep > 1:
                kp = jnp.repeat(kp, rep, axis=2)
                vp = jnp.repeat(vp, rep, axis=2)
            s = jnp.einsum("bqhd,bkhd->bhqk", q, kp,
                           preferred_element_type=jnp.float32) * scale
            kpos = jnp.arange(S, dtype=jnp.int32)
            mask = kpos[None, None, :] <= pos[:, :, None]      # [B, C, S]
            s = jnp.where(mask[:, None], s, _NEG_BIG)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vp.dtype), vp,
                           preferred_element_type=jnp.float32).astype(
                               q.dtype)
            x = x + (o.reshape(B, C, H * Dh) @ lp["wo"]).astype(cfg.dtype)
            x = _ffn(cfg, lp, x)
            return x, (kc_l, vc_l)

        x, (kc, vc) = lax.scan(body, x, (params["layers"], kc, vc))
        x = tf_lib._rmsnorm(x, params["final_norm"], cfg.norm_eps)
        logits = (x @ params["lm_head"]).astype(jnp.float32)   # [B, C, V]
        return kc, vc, jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def inject(kc, vc, blocks, k_pages, v_pages):
        """Scatter handed-off prompt pages into this pool (the
        prefill/decode disaggregation receive path). blocks
        [table_width] i32 — real target blocks first, then NULL_BLOCK
        padding whose zero pages land on the never-read null block
        (the same padding contract as the prefill bucket blocks);
        k/v_pages [L, table_width, bs, Hkv, Dh]. One compiled program
        per geometry; without it the un-jitted ``.at[].set`` fallback
        copies the ENTIRE pool per handoff instead of O(pages)."""
        kc = kc.at[:, blocks].set(k_pages.astype(kc.dtype))
        vc = vc.at[:, blocks].set(v_pages.astype(vc.dtype))
        return kc, vc

    # Donate the cache pool: steady-state decode rewrites it in place
    # instead of allocating a fresh [L, n_blocks, bs, Hkv, Dh] copy
    # per step. `length`/`offset`/`positions` stay traced (they change
    # every call); only array shapes key the jit cache.
    return (jax.jit(prefill, donate_argnums=(1, 2)),
            jax.jit(prefill_resume, donate_argnums=(1, 2)),
            jax.jit(decode, donate_argnums=(1, 2)),
            jax.jit(inject, donate_argnums=(0, 1)),
            jax.jit(verify, donate_argnums=(1, 2)))
