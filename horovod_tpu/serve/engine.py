"""Continuous-batching inference engine.

The serving analog of the training runtime: one process drives the
whole mesh, and scheduling is **iteration-level** (Orca OSDI'22 /
vLLM): every :meth:`ServeEngine.step` retires sequences that finished
on the previous iteration, expires queued requests past their
deadline, admits new requests into the running batch (one prefill
each), then runs ONE decode iteration for everything active. New
requests join the running batch mid-flight and finished sequences
leave immediately — the batch never drains to admit, which is where
the throughput win over static batching comes from on mixed-length
traffic.

Admission control is two-layered:

* **queue backpressure** — :meth:`submit` raises :class:`QueueFull`
  (503-style) once ``max_queue`` requests are waiting;
* **KV backpressure** — a request is admitted only when the block
  pool can reserve its worst case (prompt + max_new_tokens), so a
  running sequence can never hit out-of-blocks mid-decode (no
  preemption/swapping tier yet; the reservation is the simple-and-
  safe policy and `high_water` tells you how much it costs).

Deadlines are absolute engine-clock times by which a request must be
*admitted* (first token scheduled); stale requests are rejected with a
503-style result rather than burning prefill FLOPs on an answer
nobody is waiting for. The clock is injectable for tests.

Determinism: FIFO admission, stable batch-slot assignment, greedy
argmax in-jit — the same submission order always yields bitwise the
same tokens, which the parity test pins.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu.serve import decode as decode_lib
from horovod_tpu.serve.kv_cache import (
    BlockAllocator, init_kv_cache, pick_bucket,
)
from horovod_tpu.serve.metrics import ServeMetrics


class QueueFull(RuntimeError):
    """Admission-queue backpressure — shed load upstream."""
    http_status = 503


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs (model shape lives in ``TransformerConfig``)."""

    max_batch: int = 8           # decode batch slots
    max_queue: int = 64          # admission queue depth (then 503)
    block_size: int = 16         # KV tokens per block
    n_blocks: Optional[int] = None   # pool size; default = worst case
    max_prompt: int = 512        # longest admissible prompt
    max_new_tokens: int = 128    # per-request generation cap
    eos_id: Optional[int] = None
    # Shape buckets (None = powers-of-two menus). Fewer buckets = fewer
    # compiles; more buckets = less padding waste.
    batch_buckets: Optional[Tuple[int, ...]] = None
    prefill_buckets: Optional[Tuple[int, ...]] = None
    # "continuous": iteration-level admission (the point of this
    # engine). "static": admit only into an empty batch — the
    # classical serve loop, kept as the benchmark baseline.
    scheduling: str = "continuous"
    cache_dtype: Any = None      # default: model dtype


@dataclasses.dataclass
class RequestResult:
    rid: int
    status: str                  # "ok" | "expired"
    http_status: int             # 200 | 503
    tokens: List[int]
    n_prompt: int
    submitted_at: float
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None

    @property
    def first_token_latency_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at


@dataclasses.dataclass
class _Queued:
    rid: int
    prompt: List[int]
    max_new: int
    deadline: Optional[float]
    submitted_at: float


@dataclasses.dataclass
class _Seq:
    rid: int
    prompt: List[int]
    max_new: int
    blocks: List[int]
    table: np.ndarray            # [table_width] int32 physical block ids
    n_cached: int                # tokens currently in the KV cache
    generated: List[int]
    submitted_at: float
    first_token_at: float

    @property
    def last_token(self) -> int:
        return self.generated[-1]

    def finished(self, eos_id: Optional[int]) -> bool:
        return (len(self.generated) >= self.max_new
                or (eos_id is not None and self.last_token == eos_id))


def _pow2_menu(lo: int, hi: int) -> Tuple[int, ...]:
    out = []
    b = lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(out)


class ServeEngine:
    def __init__(self, model_cfg, params, serve_cfg: Optional[ServeConfig]
                 = None, mesh: Optional[Any] = None,
                 clock=time.perf_counter):
        cfg = serve_cfg or ServeConfig()
        if cfg.scheduling not in ("continuous", "static"):
            raise ValueError(f"unknown scheduling {cfg.scheduling!r}")
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.mesh = mesh
        self._params = params
        self._clock = clock

        bs = cfg.block_size
        # Prompt buckets are whole blocks (prefill writes pages).
        max_prompt_padded = -(-cfg.max_prompt // bs) * bs
        self._prefill_buckets = cfg.prefill_buckets or _pow2_menu(
            bs, max_prompt_padded)
        self._batch_buckets = cfg.batch_buckets or _pow2_menu(
            1, cfg.max_batch)
        self._table_width = -(-(max_prompt_padded + cfg.max_new_tokens) // bs)
        # Fail at construction, not mid-step after blocks are already
        # reserved: every admissible request must fit a bucket, and
        # every bucket's pages must fit the block table.
        if any(b % bs for b in self._prefill_buckets):
            raise ValueError(
                f"prefill_buckets {self._prefill_buckets} must be "
                f"multiples of block_size {bs}")
        if max(self._prefill_buckets) // bs > self._table_width:
            raise ValueError(
                f"largest prefill bucket {max(self._prefill_buckets)} "
                f"needs {max(self._prefill_buckets) // bs} blocks but "
                f"the block table holds {self._table_width}")
        pick_bucket(cfg.max_prompt, self._prefill_buckets)
        pick_bucket(cfg.max_batch, self._batch_buckets)

        n_blocks = cfg.n_blocks
        if n_blocks is None:
            # Worst case: every batch slot holds a maximal sequence
            # (+1 for the reserved null block).
            n_blocks = cfg.max_batch * self._table_width + 1
        self.allocator = BlockAllocator(n_blocks, bs)
        self.cache = init_kv_cache(model_cfg, n_blocks, bs, mesh=mesh,
                                   dtype=cfg.cache_dtype)
        self._prefill_fn, self._decode_fn = decode_lib.make_serve_fns(
            model_cfg, mesh, block_size=bs, table_width=self._table_width)

        self.metrics = ServeMetrics(clock=clock)
        self._queue: collections.deque[_Queued] = collections.deque()
        self._active: List[_Seq] = []
        self._results: Dict[int, RequestResult] = {}
        self._rids = itertools.count()

    # -- submission --------------------------------------------------

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               deadline: Optional[float] = None) -> int:
        """Enqueue a request; returns its id. Raises :class:`QueueFull`
        when the admission queue is at capacity (backpressure) and
        ``ValueError`` on shapes the engine cannot ever serve."""
        prompt = list(prompt)
        max_new = (self.cfg.max_new_tokens if max_new_tokens is None
                   else max_new_tokens)
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.cfg.max_prompt:
            raise ValueError(
                f"prompt length {len(prompt)} > max_prompt "
                f"{self.cfg.max_prompt}")
        if not 1 <= max_new <= self.cfg.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {max_new} outside [1, "
                f"{self.cfg.max_new_tokens}]")
        if len(prompt) + max_new > self.model_cfg.max_seq:
            raise ValueError(
                f"prompt+max_new {len(prompt) + max_new} > model max_seq "
                f"{self.model_cfg.max_seq}")
        need = self.allocator.blocks_for_tokens(len(prompt) + max_new)
        if need > self.allocator.n_blocks - 1:
            # Worst-case reservation exceeds the whole pool: admission
            # could never succeed and FIFO would starve every request
            # behind it — reject now, not never.
            raise ValueError(
                f"request needs {need} KV blocks worst-case but the pool "
                f"holds {self.allocator.n_blocks - 1}; raise n_blocks or "
                "lower max_new_tokens")
        if len(self._queue) >= self.cfg.max_queue:
            self.metrics.record_rejected()
            raise QueueFull(
                f"admission queue full ({self.cfg.max_queue} waiting)")
        rid = next(self._rids)
        self._queue.append(_Queued(rid, prompt, max_new, deadline,
                                   self._clock()))
        self.metrics.record_submitted()
        self.metrics.record_queue_depth(len(self._queue))
        return rid

    # -- results -----------------------------------------------------

    @property
    def pending(self) -> bool:
        return bool(self._queue or self._active)

    def result(self, rid: int) -> Optional[RequestResult]:
        return self._results.get(rid)

    @property
    def results(self) -> Dict[int, RequestResult]:
        return dict(self._results)

    # -- the scheduler iteration ------------------------------------

    def step(self) -> None:
        """One iteration: retire → expire → admit (prefill) → decode."""
        now = self._clock()
        self._retire_finished(now)
        self._expire_queued(now)
        self._admit(now)
        self._decode_once()
        self.metrics.record_queue_depth(len(self._queue))

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        for _ in range(max_steps):
            if not self.pending:
                return
            self.step()
        raise RuntimeError(f"engine still busy after {max_steps} steps")

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: Optional[int] = None) -> List[List[int]]:
        """Convenience batch API: serve ``prompts`` to completion and
        return their generated token lists in order."""
        rids = [self.submit(p, max_new_tokens) for p in prompts]
        self.run_until_idle()
        return [self._results[r].tokens for r in rids]

    # -- internals ---------------------------------------------------

    def _finish(self, seq: _Seq, now: float) -> None:
        self.allocator.free(seq.blocks)
        self._results[seq.rid] = RequestResult(
            rid=seq.rid, status="ok", http_status=200,
            tokens=list(seq.generated), n_prompt=len(seq.prompt),
            submitted_at=seq.submitted_at,
            first_token_at=seq.first_token_at, finished_at=now)
        self.metrics.record_finished()

    def _retire_finished(self, now: float) -> None:
        still = []
        for seq in self._active:
            if seq.finished(self.cfg.eos_id):
                self._finish(seq, now)
            else:
                still.append(seq)
        self._active = still

    def _expire_queued(self, now: float) -> None:
        keep: collections.deque[_Queued] = collections.deque()
        for req in self._queue:
            if req.deadline is not None and now > req.deadline:
                self._results[req.rid] = RequestResult(
                    rid=req.rid, status="expired", http_status=503,
                    tokens=[], n_prompt=len(req.prompt),
                    submitted_at=req.submitted_at, finished_at=now)
                self.metrics.record_expired()
            else:
                keep.append(req)
        self._queue = keep

    def _admit(self, now: float) -> None:
        batch_was_empty = not self._active
        while self._queue and len(self._active) < self.cfg.max_batch:
            if self.cfg.scheduling == "static" and not batch_was_empty:
                # Baseline scheduler: wait for the whole batch to
                # drain before admitting again.
                return
            req = self._queue[0]
            need = self.allocator.blocks_for_tokens(
                len(req.prompt) + req.max_new)
            if not self.allocator.can_alloc(need):
                # KV backpressure (FIFO: no overtaking, so tail
                # latency stays predictable under load).
                return
            self._queue.popleft()
            self._prefill(req, self.allocator.alloc(need))

    def _prefill(self, req: _Queued, blocks: List[int]) -> None:
        import jax

        plen = len(req.prompt)
        bucket = pick_bucket(plen, self._prefill_buckets)
        toks = np.zeros(bucket, np.int32)
        toks[:plen] = req.prompt
        table = np.zeros(self._table_width, np.int32)
        table[:len(blocks)] = blocks
        t0 = self._clock()
        with jax.profiler.TraceAnnotation("serve:prefill"):
            kc, vc, tok = self._prefill_fn(
                self._params, self.cache.k, self.cache.v, toks,
                np.int32(plen), table)
            tok = int(tok)  # host sync — the step is done when this is
        now = self._clock()
        self.cache.k, self.cache.v = kc, vc
        self.metrics.record_prefill(t0, now - t0, plen)
        self.metrics.record_first_token(now - req.submitted_at)
        seq = _Seq(rid=req.rid, prompt=req.prompt, max_new=req.max_new,
                   blocks=blocks, table=table, n_cached=plen,
                   generated=[tok], submitted_at=req.submitted_at,
                   first_token_at=now)
        if seq.finished(self.cfg.eos_id):
            self._finish(seq, now)
        else:
            self._active.append(seq)

    def _decode_once(self) -> None:
        import jax

        if not self._active:
            return
        n = len(self._active)
        bucket = pick_bucket(n, self._batch_buckets)
        tokens = np.zeros(bucket, np.int32)
        positions = np.zeros(bucket, np.int32)
        tables = np.zeros((bucket, self._table_width), np.int32)
        for i, seq in enumerate(self._active):
            tokens[i] = seq.last_token
            positions[i] = seq.n_cached
            tables[i] = seq.table
        t0 = self._clock()
        with jax.profiler.TraceAnnotation("serve:decode"):
            kc, vc, out = self._decode_fn(
                self._params, self.cache.k, self.cache.v, tokens,
                positions, tables)
            out = np.asarray(out)  # host sync
        dur = self._clock() - t0
        self.cache.k, self.cache.v = kc, vc
        for i, seq in enumerate(self._active):
            seq.n_cached += 1
            seq.generated.append(int(out[i]))
        self.metrics.record_decode(t0, dur, n, self.cfg.max_batch)
