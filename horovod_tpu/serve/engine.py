"""Continuous-batching inference engine.

The serving analog of the training runtime: one process drives the
whole mesh, and scheduling is **iteration-level** (Orca OSDI'22 /
vLLM): every :meth:`ServeEngine.step` retires sequences that finished
on the previous iteration, expires queued requests past their
deadline, admits new requests into the running batch (one prefill
each), then runs ONE decode iteration for everything active. New
requests join the running batch mid-flight and finished sequences
leave immediately — the batch never drains to admit, which is where
the throughput win over static batching comes from on mixed-length
traffic.

Admission control is two-layered:

* **queue backpressure** — :meth:`submit` raises :class:`QueueFull`
  (503-style) once ``max_queue`` requests are waiting;
* **KV backpressure** — a request is admitted only when the block
  pool can reserve its worst case (prompt + max_new_tokens), so a
  running sequence can never hit out-of-blocks mid-decode (no
  preemption/swapping tier yet; the reservation is the simple-and-
  safe policy and `high_water` tells you how much it costs).

Two throughput levers sit on top of the paged layout:

* **prefix caching** (``ServeConfig.prefix_caching``) — admission
  walks the prompt's chained block hashes against the allocator's
  content index; every leading whole block already cached is mapped
  straight into the new sequence's block table (one refcount, zero
  FLOPs) and only the unmatched suffix is prefilled. Full prompt
  blocks are published back to the index after they are written, so
  a fleet of requests sharing a system prompt pays its prefill once.
* **chunked prefill** (``ServeConfig.prefill_chunk``) — a long
  suffix is split into block-aligned chunks processed across
  successive :meth:`ServeEngine.step` iterations, interleaved with
  decode, so one long prompt no longer monopolizes an iteration and
  spikes every in-flight sequence's per-token latency. A chunking
  sequence holds all its reserved blocks but does not enter the
  decode batch until its prefill completes.

Deadlines are absolute engine-clock times by which a request must be
*admitted* (first token scheduled); stale requests are rejected with a
**structured rejection** (machine-readable ``reason``, the request's
``deadline_class``, and a ``retry_after_s`` estimate derived from the
queue depth and the engine's recent retirement rate) rather than a
blanket 503 — and rather than burning prefill FLOPs on an answer
nobody is waiting for. The clock is injectable for tests.

Fleet hooks (used by :mod:`horovod_tpu.serve.router`, all cheap
host-side reads or bounded mutations — none of them step the engine):

* :meth:`admission_snapshot` — occupancy / free KV blocks / queue
  depth, what a router polls to pick a replica;
* :meth:`cached_chain_len` — how many leading blocks of a prompt's
  hash chain this replica's content index already holds (the
  cache-affinity placement signal);
* :meth:`withdraw` — reclaim a still-queued request (replica drain);
* ``submit(..., prefill_only=True)`` + :meth:`handoff_ready` /
  :meth:`export_prefilled` / :meth:`inject_prefilled` — the
  disaggregated prefill/decode path: a prefill replica runs the
  prompt through the existing chunked-prefill machinery, parks the
  finished sequence, and the router moves its K/V pages (bitwise) to
  a decode replica's pool where decoding continues.

Determinism: FIFO admission, stable batch-slot assignment, greedy
argmax in-jit — the same submission order always yields bitwise the
same tokens, which the parity test pins.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu.serve import decode as decode_lib
from horovod_tpu.serve.kv_cache import (
    BlockAllocator, hash_chain, init_kv_cache, pick_bucket,
)
from horovod_tpu.serve.metrics import ServeMetrics


class QueueFull(RuntimeError):
    """Admission-queue backpressure — shed load upstream. Carries the
    structured-rejection fields so a caller (or the fleet router) can
    tell its client *when* to retry instead of hammering a 503."""
    http_status = 503

    def __init__(self, msg: str, *, reason: str = "queue_full",
                 queue_depth: int = 0,
                 retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.reason = reason
        self.queue_depth = queue_depth
        self.retry_after_s = retry_after_s


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs (model shape lives in ``TransformerConfig``)."""

    max_batch: int = 8           # decode batch slots
    max_queue: int = 64          # admission queue depth (then 503)
    block_size: int = 16         # KV tokens per block
    n_blocks: Optional[int] = None   # pool size; default = worst case
    max_prompt: int = 512        # longest admissible prompt
    max_new_tokens: int = 128    # per-request generation cap
    eos_id: Optional[int] = None
    # Shape buckets (None = powers-of-two menus). Fewer buckets = fewer
    # compiles; more buckets = less padding waste.
    batch_buckets: Optional[Tuple[int, ...]] = None
    prefill_buckets: Optional[Tuple[int, ...]] = None
    # "continuous": iteration-level admission (the point of this
    # engine). "static": admit only into an empty batch — the
    # classical serve loop, kept as the benchmark baseline.
    scheduling: str = "continuous"
    cache_dtype: Any = None      # default: model dtype
    # Map whole-block prompt prefixes out of the content-addressed
    # block cache instead of recomputing them (hit rate shows up in
    # metrics as prefix_cache_hit_rate). Off = every prompt pays full
    # prefill FLOPs, the pre-cache behavior.
    prefix_caching: bool = True
    # Max prefill tokens processed per engine step (block-aligned).
    # None = unbounded: every admitted request's whole suffix
    # prefills in its admission step (monolithic prefill). Set to
    # bound the prefill work one step can absorb, so long prompts
    # stream in across iterations interleaved with decode.
    prefill_chunk: Optional[int] = None
    # In-jit mesh compression for the decode/prefill programs (a
    # hvd.Compression member; None = uncompressed, bitwise the
    # pre-existing programs) — the serving face of the training
    # planes' one knob. See decode.make_serve_fns.
    compression: Any = None
    # Speculative decoding (serve/speculative.py): `draft` is the
    # sub-config naming the draft transformer (a
    # speculative.DraftConfig — model config + params seed + cache
    # dtype; it inherits THIS engine's block geometry), and `spec_k`
    # is how many tokens the draft proposes per scheduler iteration,
    # all verified in ONE chunked target step. Both set = speculation
    # on (greedy streams stay bitwise plain decode's); both unset =
    # plain decode, byte for byte the pre-speculative engine.
    draft: Any = None
    spec_k: int = 0


@dataclasses.dataclass
class RequestResult:
    rid: int
    status: str                  # "ok" | "expired" | "shed"
    http_status: int             # 200 | 503
    tokens: List[int]
    n_prompt: int
    submitted_at: float
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    # Structured rejection (status != "ok"): machine-readable reason
    # ("deadline_expired" | "shed_low_class"), the request's deadline
    # class, and how long the client should back off — estimated from
    # the queue depth times the engine's recent retirement interval.
    reason: Optional[str] = None
    deadline_class: int = 0
    retry_after_s: Optional[float] = None

    @property
    def first_token_latency_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at


@dataclasses.dataclass
class _Queued:
    rid: int
    prompt: List[int]
    max_new: int
    deadline: Optional[float]
    submitted_at: float
    chain: List[bytes]           # content-hash chain, hashed once at
    #                              submit (not per admission retry)
    deadline_class: int = 0
    prefill_only: bool = False   # park for handoff instead of decoding
    trace: int = 0               # distributed trace id (0 = unsampled)


@dataclasses.dataclass
class _Seq:
    rid: int
    prompt: List[int]
    max_new: int
    blocks: List[int]            # refs held: shared prefix + private
    table: np.ndarray            # [table_width] int32 physical block ids
    n_cached: int                # tokens currently in the KV cache
    generated: List[int]
    submitted_at: float
    chain: List[bytes]           # content-hash chain, one per full
    #                              prompt block (empty: caching off)
    registered: int              # prompt blocks published (or mapped
    #                              from the cache) so far
    first_token_at: Optional[float] = None
    last_prefill_tok: int = 0    # argmax of the newest chunk's last
    #                              real position; the first generated
    #                              token once prefill completes
    deadline_class: int = 0
    prefill_only: bool = False
    trace: int = 0               # distributed trace id (0 = unsampled)

    @property
    def last_token(self) -> int:
        return self.generated[-1]

    def finished(self, eos_id: Optional[int]) -> bool:
        return (len(self.generated) >= self.max_new
                or (eos_id is not None and self.last_token == eos_id))


@dataclasses.dataclass
class PrefillHandoff:
    """A sequence packaged for another replica: its K/V pages as host
    copies plus the request state needed to continue decoding
    elsewhere. The pages are bitwise copies and the decode math is
    position-dependent only, so a handed-off sequence decodes to
    exactly the tokens it would have produced in place.

    Two producers share this shape: :meth:`ServeEngine.export_prefilled`
    (a completed prefill leaving a prefill-pool replica — ``n_cached``
    == prompt length, ``generated`` == the one prefill-emitted token)
    and :meth:`ServeEngine.export_running` (a mid-decode sequence
    leaving a draining replica — ``n_cached`` covers every token whose
    K/V is in the pages, ``generated`` everything emitted so far). The
    consumer is one :meth:`ServeEngine.inject_prefilled` either way.
    """

    prompt: List[int]
    max_new: int
    generated: List[int]         # tokens emitted so far (>= 1)
    submitted_at: float
    first_token_at: float
    deadline_class: int
    chain: List[bytes]           # content-hash chain (may be empty)
    k_pages: Any                 # [L, n_pages, bs, Hkv, Dh]
    v_pages: Any
    block_size: int
    n_cached: int                # tokens covered by the pages
    trace_id: int = 0            # distributed trace id (0 = unsampled)

    @property
    def n_pages(self) -> int:
        return int(self.k_pages.shape[1])


class RetireEma:
    """Inter-retirement interval EMA: the drain-rate signal behind
    every ``retry_after_s`` estimate. One implementation shared by
    the engine and the fleet router so the smoothing (0.8/0.2,
    first-observation seeding) can never diverge between tiers."""

    def __init__(self):
        self.value = 0.0
        self._last: Optional[float] = None

    def observe(self, now: float) -> None:
        if self._last is not None:
            dt = max(now - self._last, 0.0)
            self.value = (0.8 * self.value + 0.2 * dt
                          if self.value else dt)
        self._last = now

    def retry_after(self, queue_depth: int) -> float:
        """Back-off estimate: requests ahead x the recent
        inter-retirement interval. 0.0 before any retirement."""
        return round(queue_depth * self.value, 6)


def validate_request(serve_cfg: ServeConfig, model_cfg, n_pool_blocks: int,
                     prompt: List[int], max_new: int,
                     deadline_class: int) -> None:
    """Shared admission validation — ONE implementation for both the
    engine and the fleet router. The router accepts requests before
    any engine sees them; if its checks ever drifted looser than the
    engine's, an accepted request would blow ValueError out of a later
    placement step (popped from the queue, leaked without a result)
    instead of rejecting at submit."""
    if not prompt:
        raise ValueError("empty prompt")
    if len(prompt) > serve_cfg.max_prompt:
        raise ValueError(
            f"prompt length {len(prompt)} > max_prompt "
            f"{serve_cfg.max_prompt}")
    if not 1 <= max_new <= serve_cfg.max_new_tokens:
        raise ValueError(
            f"max_new_tokens {max_new} outside [1, "
            f"{serve_cfg.max_new_tokens}]")
    if len(prompt) + max_new > model_cfg.max_seq:
        raise ValueError(
            f"prompt+max_new {len(prompt) + max_new} > model max_seq "
            f"{model_cfg.max_seq}")
    if deadline_class < 0:
        raise ValueError(f"deadline_class {deadline_class} < 0")
    need = -(-(len(prompt) + max_new) // serve_cfg.block_size)
    if need > n_pool_blocks - 1:
        # Worst-case reservation exceeds the whole pool: admission
        # could never succeed and FIFO would starve every request
        # behind it — reject now, not never.
        raise ValueError(
            f"request needs {need} KV blocks worst-case but the pool "
            f"holds {n_pool_blocks - 1}; raise n_blocks or lower "
            "max_new_tokens")


def _pow2_menu(lo: int, hi: int) -> Tuple[int, ...]:
    out = []
    b = lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(out)


class ServeEngine:
    def __init__(self, model_cfg, params, serve_cfg: Optional[ServeConfig]
                 = None, mesh: Optional[Any] = None,
                 clock=time.perf_counter,
                 instance: Optional[str] = None):
        cfg = serve_cfg or ServeConfig()
        if cfg.scheduling not in ("continuous", "static"):
            raise ValueError(f"unknown scheduling {cfg.scheduling!r}")
        if (cfg.draft is None) != (cfg.spec_k == 0) or cfg.spec_k < 0:
            raise ValueError(
                f"draft= and spec_k= go together (draft="
                f"{'set' if cfg.draft is not None else None}, spec_k="
                f"{cfg.spec_k}): set both for speculative decoding, "
                "neither for plain decode")
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.mesh = mesh
        self._params = params
        self._clock = clock

        bs = cfg.block_size
        # Prompt buckets are whole blocks (prefill writes pages).
        max_prompt_padded = -(-cfg.max_prompt // bs) * bs
        self._prefill_buckets = cfg.prefill_buckets or _pow2_menu(
            bs, max_prompt_padded)
        self._batch_buckets = cfg.batch_buckets or _pow2_menu(
            1, cfg.max_batch)
        self._table_width = -(-(max_prompt_padded + cfg.max_new_tokens) // bs)
        # Fail at construction, not mid-step after blocks are already
        # reserved: every admissible request must fit a bucket, and
        # every bucket's pages must fit the block table.
        if any(b % bs for b in self._prefill_buckets):
            raise ValueError(
                f"prefill_buckets {self._prefill_buckets} must be "
                f"multiples of block_size {bs}")
        if max(self._prefill_buckets) // bs > self._table_width:
            raise ValueError(
                f"largest prefill bucket {max(self._prefill_buckets)} "
                f"needs {max(self._prefill_buckets) // bs} blocks but "
                f"the block table holds {self._table_width}")
        pick_bucket(cfg.max_prompt, self._prefill_buckets)
        pick_bucket(cfg.max_batch, self._batch_buckets)
        if cfg.prefill_chunk is not None:
            # Chunks must start block-aligned (the resume fn's page
            # writes are blockwise) and fit a bucket.
            if cfg.prefill_chunk < bs or cfg.prefill_chunk % bs:
                raise ValueError(
                    f"prefill_chunk {cfg.prefill_chunk} must be a "
                    f"positive multiple of block_size {bs}")
            pick_bucket(cfg.prefill_chunk, self._prefill_buckets)

        # Inject pad-width menu, in BLOCK units: the prefill buckets
        # (prompt-only handoffs keep their existing programs) plus the
        # full table width (a migrated RUNNING sequence may carry
        # prompt+generated pages beyond the largest prompt bucket).
        self._inject_widths = tuple(sorted(
            {b // bs for b in self._prefill_buckets}
            | {self._table_width}))

        n_blocks = cfg.n_blocks
        if n_blocks is None:
            # Worst case: every batch slot holds a maximal sequence
            # (+1 for the reserved null block).
            n_blocks = cfg.max_batch * self._table_width + 1
        self.allocator = BlockAllocator(n_blocks, bs)
        self.cache = init_kv_cache(model_cfg, n_blocks, bs, mesh=mesh,
                                   dtype=cfg.cache_dtype)
        (self._prefill_fn, self._resume_fn, self._decode_fn,
         self._inject_fn, self._verify_fn) = decode_lib.make_serve_fns(
             model_cfg, mesh, block_size=bs,
             table_width=self._table_width, compression=cfg.compression)
        # Jitted page gather for handoff export — the twin of the
        # inject scatter. Op-by-op fancy indexing pays a full dispatch
        # per export (measured ~3x the compiled gather on the bench
        # payloads); widths ride the same bucket menu as inject so one
        # program per bucket serves every export.
        import jax as _jax
        self._export_fn = _jax.jit(lambda k, v, i: (k[:, i], v[:, i]))

        self.metrics = ServeMetrics(clock=clock, instance=instance)
        self.metrics.attach_allocator(self.allocator)
        self._queue: collections.deque[_Queued] = collections.deque()
        self._active: List[_Seq] = []
        # Admitted sequences whose prefill has not completed: they
        # hold their block reservation and consume a batch slot, but
        # only join the decode batch once prefill finishes.
        self._prefilling: List[_Seq] = []
        # prefill_only sequences whose prefill completed: parked (with
        # their prompt blocks held) until the router exports them to a
        # decode replica. Not counted in `pending` — draining them is
        # the router's job, not the step loop's.
        self._handoff: Dict[int, _Seq] = {}
        self._results: Dict[int, RequestResult] = {}
        self._rids = itertools.count()
        # Staged (chunked) injects in flight: token -> {meta, blocks,
        # n_pages, cursor}. Invisible to admission/decode until commit;
        # an abort returns the block reservation.
        self._inject_staging: Dict[int, Dict[str, Any]] = {}
        self._inject_tokens = itertools.count()
        # Drain-rate signal behind retry_after_s estimates.
        self._retire_ema = RetireEma()
        # Speculative side-car: draft params + mirror KV pool + the
        # propose/verify/accept round that replaces _decode_once.
        self._spec = None
        if cfg.draft is not None:
            from horovod_tpu.serve.speculative import SpecDecoder
            self._spec = SpecDecoder(self)

    # -- submission --------------------------------------------------

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               deadline: Optional[float] = None,
               deadline_class: int = 0,
               prefill_only: bool = False,
               chain: Optional[List[bytes]] = None,
               trace_id: int = 0) -> int:
        """Enqueue a request; returns its id. Raises :class:`QueueFull`
        when the admission queue is at capacity (backpressure) and
        ``ValueError`` on shapes the engine cannot ever serve.
        ``deadline_class`` rides rejections so upstream shedding can
        order them; ``prefill_only`` parks the sequence for
        :meth:`export_prefilled` instead of decoding it here;
        ``chain`` is the prompt's precomputed hash chain (the router
        hashed it once at fleet admission — passing it through keeps
        the PR 4 hash-ONCE discipline across tiers; trusted, must
        match ``hash_chain(prompt, block_size)``); ``trace_id`` is the
        router-minted distributed trace id (0 = unsampled) that tags
        this request's prefill/decode spans (docs/observability.md)."""
        prompt = list(prompt)
        max_new = (self.cfg.max_new_tokens if max_new_tokens is None
                   else max_new_tokens)
        validate_request(self.cfg, self.model_cfg,
                         self.allocator.n_blocks, prompt, max_new,
                         deadline_class)
        if len(self._queue) >= self.cfg.max_queue:
            self.metrics.record_rejected()
            raise QueueFull(
                f"admission queue full ({self.cfg.max_queue} waiting)",
                queue_depth=len(self._queue),
                retry_after_s=self._retry_after())
        rid = next(self._rids)
        chain = ((hash_chain(prompt, self.cfg.block_size)
                  if chain is None else chain)
                 if self.cfg.prefix_caching else [])
        self._queue.append(_Queued(rid, prompt, max_new, deadline,
                                   self._clock(), chain,
                                   deadline_class=deadline_class,
                                   prefill_only=prefill_only,
                                   trace=trace_id))
        self.metrics.record_submitted()
        self.metrics.record_queue_depth(len(self._queue))
        return rid

    # -- results -----------------------------------------------------

    @property
    def pending(self) -> bool:
        return bool(self._queue or self._prefilling or self._active)

    def result(self, rid: int) -> Optional[RequestResult]:
        return self._results.get(rid)

    @property
    def results(self) -> Dict[int, RequestResult]:
        return dict(self._results)

    # -- fleet hooks (cheap host-side reads; nothing here steps the
    #    engine or touches the device) ------------------------------

    def _retry_after(self) -> float:
        return self._retire_ema.retry_after(len(self._queue))

    def admission_snapshot(self) -> Dict[str, float]:
        """Router-facing admission state: occupancy, free KV blocks,
        queue depth. Pure host-side counter reads — a router can poll
        every replica per placement decision without stepping anyone
        or syncing a device value."""
        n_run = len(self._active) + len(self._prefilling)
        return {
            "queue_depth": len(self._queue),
            "queue_slots_free": self.cfg.max_queue - len(self._queue),
            "running": n_run,
            "batch_slots_free": self.cfg.max_batch - n_run,
            "occupancy": n_run / self.cfg.max_batch,
            "kv_blocks_free": self.allocator.n_free,
            "kv_blocks_used": self.allocator.n_used,
            "handoff_parked": len(self._handoff),
            "retry_after_s": self._retry_after(),
        }

    def cached_chain_len(self, chain: Sequence[bytes]) -> int:
        """Leading blocks of ``chain`` this engine's content index
        holds (live or cached) — the prefix-affinity placement signal.
        Non-mutating (`peek`): polling it from a router never inflates
        hit counters or churns the LRU order."""
        n = 0
        for h in chain:
            if self.allocator.peek(h) is None:
                break
            n += 1
        return n

    def withdraw(self, rid: int) -> bool:
        """Remove a still-queued (never admitted) request, dropping it
        without a result. False if ``rid`` is unknown, already
        admitted, or already resolved — the caller keeps its own copy
        of the request if it intends to resubmit elsewhere (this is
        the router's replica-drain path)."""
        for i, req in enumerate(self._queue):
            if req.rid == rid:
                del self._queue[i]
                # Un-count the submission: the caller re-submits the
                # request elsewhere (which counts it again there), so
                # fleet-summed submitted = finished+expired+rejected
                # stays balanced across drains.
                self.metrics.record_withdrawn()
                self.metrics.record_queue_depth(len(self._queue))
                return True
        return False

    # -- the scheduler iteration ------------------------------------

    def step(self) -> None:
        """One iteration: retire → expire → admit → prefill chunk(s)
        → decode."""
        now = self._clock()
        self._retire_finished(now)
        self._expire_queued(now)
        self._admit(now)
        self._advance_prefills()
        self._decode_once()
        self.metrics.record_queue_depth(len(self._queue))

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        for _ in range(max_steps):
            if not self.pending:
                return
            self.step()
        raise RuntimeError(f"engine still busy after {max_steps} steps")

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: Optional[int] = None) -> List[List[int]]:
        """Convenience batch API: serve ``prompts`` to completion and
        return their generated token lists in order."""
        rids = [self.submit(p, max_new_tokens) for p in prompts]
        self.run_until_idle()
        return [self._results[r].tokens for r in rids]

    # -- internals ---------------------------------------------------

    def _finish(self, seq: _Seq, now: float) -> None:
        self.allocator.free(seq.blocks)
        if self._spec is not None:
            self._spec.drop(seq.rid)
        self._results[seq.rid] = RequestResult(
            rid=seq.rid, status="ok", http_status=200,
            tokens=list(seq.generated), n_prompt=len(seq.prompt),
            submitted_at=seq.submitted_at,
            first_token_at=seq.first_token_at, finished_at=now,
            deadline_class=seq.deadline_class)
        self._retire_ema.observe(now)
        self.metrics.record_finished()

    def _retire_finished(self, now: float) -> None:
        still = []
        for seq in self._active:
            if seq.finished(self.cfg.eos_id):
                self._finish(seq, now)
            else:
                still.append(seq)
        self._active = still

    def _expire_queued(self, now: float) -> None:
        keep: collections.deque[_Queued] = collections.deque()
        for req in self._queue:
            if req.deadline is not None and now > req.deadline:
                # Structured rejection, not a blanket 503: the client
                # learns WHY (deadline passed in queue), at what
                # priority it was classified, and when a retry might
                # actually get served.
                self._results[req.rid] = RequestResult(
                    rid=req.rid, status="expired", http_status=503,
                    tokens=[], n_prompt=len(req.prompt),
                    submitted_at=req.submitted_at, finished_at=now,
                    reason="deadline_expired",
                    deadline_class=req.deadline_class,
                    retry_after_s=self._retry_after())
                self.metrics.record_expired()
            else:
                keep.append(req)
        self._queue = keep

    def _admit(self, now: float) -> None:
        batch_was_empty = not self._active and not self._prefilling
        while (self._queue and
               len(self._active) + len(self._prefilling)
               < self.cfg.max_batch):
            if self.cfg.scheduling == "static" and not batch_was_empty:
                # Baseline scheduler: wait for the whole batch to
                # drain before admitting again.
                return
            req = self._queue[0]
            plen = len(req.prompt)
            # A prefill-only sequence never decodes here — it writes
            # prompt pages and leaves — so reserving its max_new tail
            # would waste prefill-pool capacity for nothing.
            need = self.allocator.blocks_for_tokens(
                plen if req.prefill_only else plen + req.max_new)
            # Walk the chain against the content index; every leading
            # whole block already cached maps into this sequence's
            # table with one refcount, zero FLOPs. Capped at plen-1
            # tokens: the final prompt token must run through the
            # model — its logits are the first generated token. The
            # first walk is a non-mutating peek: a blocked request
            # retries admission every step, and taking/releasing refs
            # here would inflate the hit counters and churn the LRU
            # order with reuse that never happened.
            matchable = req.chain[:(plen - 1) // self.cfg.block_size]
            n_match, n_revive = 0, 0
            for h in matchable:
                b = self.allocator.peek(h)
                if b is None:
                    break
                n_match += 1
                if self.allocator.refcount(b) == 0:
                    # Reviving a refcount-0 cached block consumes a
                    # unit of n_free just like a fresh allocation, so
                    # it must count against capacity — or an
                    # overcommitted pool passes this check and then
                    # blows OutOfBlocks mid-admission.
                    n_revive += 1
            if not self.allocator.can_alloc(need - n_match + n_revive):
                # KV backpressure (FIFO: no overtaking, so tail
                # latency stays predictable under load).
                return
            self._queue.popleft()
            # Commit: nothing mutated between peek and acquire, so
            # the same blocks resolve — and hits (plus the one
            # boundary miss) count once, for an admission that
            # actually happened.
            matched: List[int] = []
            for h in matchable:
                b = self.allocator.acquire_cached(h)
                if b is None:
                    break
                matched.append(b)
            assert len(matched) == n_match
            blocks = matched + self.allocator.alloc(need - n_match)
            table = np.zeros(self._table_width, np.int32)
            table[:len(blocks)] = blocks
            n_hit = len(matched) * self.cfg.block_size
            self.metrics.record_prefix_lookup(n_hit, plen - n_hit)
            self._prefilling.append(_Seq(
                rid=req.rid, prompt=req.prompt, max_new=req.max_new,
                blocks=blocks, table=table, n_cached=n_hit,
                generated=[], submitted_at=req.submitted_at,
                chain=req.chain, registered=len(matched),
                deadline_class=req.deadline_class,
                prefill_only=req.prefill_only,
                trace=req.trace))

    def _advance_prefills(self) -> None:
        """Run prefill chunks FIFO across admitted-but-incomplete
        sequences, bounded per step by ``prefill_chunk`` tokens
        (always at least one chunk, so progress is guaranteed). With
        ``prefill_chunk=None`` every waiting suffix completes this
        step — the monolithic behavior."""
        budget = self.cfg.prefill_chunk
        spent = 0
        while self._prefilling and (budget is None or spent < budget):
            seq = self._prefilling[0]
            self._extend_prefix_match(seq)
            remaining = len(seq.prompt) - seq.n_cached
            if budget is None:
                chunk = remaining
            else:
                # Cap by the UNSPENT budget, not the full chunk size:
                # several queued suffixes could otherwise spend up to
                # 2N-1 tokens in one step. Non-final chunks must end
                # block-aligned (the next chunk's pages start there).
                chunk = min(remaining, budget - spent)
                if chunk < remaining:
                    chunk -= chunk % self.cfg.block_size
                    if chunk == 0:
                        break
            spent += self._run_prefill_chunk(seq, chunk)
            if seq.n_cached >= len(seq.prompt):
                self._prefilling.pop(0)
                self._complete_prefill(seq)

    def _extend_prefix_match(self, seq: _Seq) -> None:
        """Retry the cache walk just before prefilling. Admission in a
        burst step matches against a cache its same-step siblings
        haven't populated yet (they register at prefill, after the
        admission loop); by prefill time an identical prefix admitted
        one slot earlier IS published, so a second walk converts those
        would-be prefill tokens into hits. Safe whenever the cursor
        sits on a whole-block boundary with every block up to it
        published or mapped: the swapped slots hold no K/V yet, and
        the displaced private blocks return to the pool."""
        if (not self.cfg.prefix_caching
                or seq.n_cached != seq.registered * self.cfg.block_size):
            return
        plen = len(seq.prompt)
        extended = 0
        for i in range(seq.registered,
                       (plen - 1) // self.cfg.block_size):
            # peek first: this walk reruns at every block-aligned
            # chunk boundary, and a cold prompt would otherwise log
            # one spurious miss per chunk.
            if self.allocator.peek(seq.chain[i]) is None:
                break
            b = self.allocator.acquire_cached(seq.chain[i])
            if b is None:
                break
            self.allocator.free([seq.blocks[i]])
            seq.blocks[i] = b
            seq.table[i] = b
            seq.n_cached += self.cfg.block_size
            seq.registered += 1
            extended += self.cfg.block_size
        if extended:
            self.metrics.record_prefix_extend(extended)

    def _run_prefill_chunk(self, seq: _Seq, chunk: int) -> int:
        import jax

        plen = len(seq.prompt)
        offset = seq.n_cached
        toks = np.zeros(pick_bucket(chunk, self._prefill_buckets), np.int32)
        toks[:chunk] = seq.prompt[offset:offset + chunk]
        t0 = self._clock()
        with jax.profiler.TraceAnnotation("serve:prefill"):
            if offset == 0 and chunk == plen:
                # Whole cold prompt: the monolithic program (exactly
                # the pre-cache code path, and the cheaper attention —
                # prompt-local instead of a full table gather).
                kc, vc, tok = self._prefill_fn(
                    self._params, self.cache.k, self.cache.v, toks,
                    np.int32(plen), seq.table)
            else:
                kc, vc, tok = self._resume_fn(
                    self._params, self.cache.k, self.cache.v, toks,
                    np.int32(offset), np.int32(chunk), seq.table)
            tok = int(tok)  # host sync — the step is done when this is
        dur = self._clock() - t0
        self.cache.k, self.cache.v = kc, vc
        seq.n_cached = offset + chunk
        seq.last_prefill_tok = tok
        self.metrics.record_prefill(t0, dur, chunk, offset=offset,
                                    trace=seq.trace)
        if self.cfg.prefix_caching:
            # Publish the prompt blocks this chunk filled. A losing
            # race (hash already published by a concurrent twin) keeps
            # the private copy anonymous — register() no-ops.
            n_full = seq.n_cached // self.cfg.block_size
            for i in range(seq.registered, n_full):
                self.allocator.register(seq.blocks[i], seq.chain[i])
            seq.registered = max(seq.registered, n_full)
        return chunk

    def _complete_prefill(self, seq: _Seq) -> None:
        now = self._clock()
        seq.generated.append(seq.last_prefill_tok)
        seq.first_token_at = now
        self.metrics.record_first_token(now - seq.submitted_at)
        if seq.finished(self.cfg.eos_id):
            # One-token requests (or an immediate eos) finish right
            # here even in prefill_only mode — nothing left to hand
            # off, so the result stays on this replica.
            self._finish(seq, now)
        elif seq.prefill_only:
            self._handoff[seq.rid] = seq
        else:
            self._active.append(seq)

    # -- prefill/decode disaggregation (KV handoff) ------------------

    def handoff_ready(self) -> List[int]:
        """rids of prefill-only sequences whose prefill completed and
        which are parked awaiting :meth:`export_prefilled`."""
        return list(self._handoff)

    def export_prefilled(self, rid: int) -> PrefillHandoff:
        """Pop a parked prefill-only sequence: copy its written K/V
        pages off this replica's pool, free its blocks, and return the
        package a decode replica feeds to :meth:`inject_prefilled`.
        The page copy is bitwise, so the handoff changes *where*
        decode runs, never *what* it computes."""
        return self._export_seq(self._handoff.pop(rid))

    def _export_seq(self, seq: _Seq) -> PrefillHandoff:
        """Package ``seq`` for another replica: bitwise page copies of
        every block its cached tokens touch (the partial tail block
        rides whole — its bytes past ``n_cached`` are never attended
        to, the same null-padding contract decode relies on), then
        free the local reservation."""
        n_blk = self.allocator.blocks_for_tokens(seq.n_cached)
        width = pick_bucket(n_blk, self._inject_widths)
        idx = np.zeros(width, np.int32)   # pad gathers the null block
        idx[:n_blk] = seq.blocks[:n_blk]
        k_g, v_g = self._export_fn(self.cache.k, self.cache.v, idx)
        if n_blk == width:
            k_pages = np.asarray(k_g)
            v_pages = np.asarray(v_g)
        else:
            # Trim the padding rows on the host; contiguous because
            # the wire layer ships the buffer as-is.
            k_pages = np.ascontiguousarray(np.asarray(k_g)[:, :n_blk])
            v_pages = np.ascontiguousarray(np.asarray(v_g)[:, :n_blk])
        self.allocator.free(seq.blocks)
        if self._spec is not None:
            self._spec.drop(seq.rid)
        self.metrics.record_handoff_out()
        return PrefillHandoff(
            prompt=list(seq.prompt), max_new=seq.max_new,
            generated=list(seq.generated),
            submitted_at=seq.submitted_at,
            first_token_at=seq.first_token_at,
            deadline_class=seq.deadline_class, chain=list(seq.chain),
            k_pages=k_pages, v_pages=v_pages,
            block_size=self.cfg.block_size, n_cached=seq.n_cached,
            trace_id=seq.trace)

    def running_exportable(self) -> List[int]:
        """rids of RUNNING (decoding) sequences a drain could migrate
        right now: active, prefill complete, and not already finished
        (a finished-but-unretired sequence must retire HERE — exporting
        it would decode it past its cap on the target)."""
        return [s.rid for s in self._active
                if not s.finished(self.cfg.eos_id)]

    def export_running(self, rid: int) -> PrefillHandoff:
        """Pop a RUNNING sequence mid-decode and package it for
        :meth:`inject_prefilled` on another replica — the migrating
        half of a drain. Everything the sequence has computed (prompt
        AND generated-token K/V) moves bitwise, so the remaining
        tokens decode to exactly what they would have been in place."""
        for i, seq in enumerate(self._active):
            if seq.rid == rid:
                break
        else:
            raise KeyError(f"no running sequence {rid}")
        if seq.finished(self.cfg.eos_id):
            raise ValueError(
                f"sequence {rid} already finished — retire it here "
                "instead of migrating it")
        del self._active[i]
        return self._export_seq(seq)

    def inject_prefilled(self, h: PrefillHandoff) -> int:
        """Admit a handed-off sequence straight into the decode batch:
        reserve its worst-case blocks, scatter its pages into this
        replica's pool, and decode onward from the last emitted token.
        The handoff may be a completed prefill (pool split) or a
        mid-decode RUNNING sequence (migrating drain) — ``n_cached``
        says how many tokens the pages cover either way. Raises
        :class:`QueueFull` (no batch slot) or
        :class:`~horovod_tpu.serve.kv_cache.OutOfBlocks` — the router
        checks :meth:`admission_snapshot` capacity first, so hitting
        either here is a router bug, not backpressure.

        Implemented as the one-chunk case of the staged inject
        (:meth:`inject_begin` / :meth:`inject_chunk` /
        :meth:`inject_commit`) — the relayed and direct migration
        paths run literally the same scatter, which is what makes the
        bitwise direct-vs-relayed parity pin in tests/test_rpc.py a
        tautology rather than a hope."""
        token = self.inject_begin({
            "prompt": h.prompt, "max_new": h.max_new,
            "generated": h.generated, "submitted_at": h.submitted_at,
            "first_token_at": h.first_token_at,
            "deadline_class": h.deadline_class, "chain": h.chain,
            "block_size": h.block_size, "n_cached": h.n_cached,
            "n_pages": h.n_pages, "trace_id": h.trace_id})
        self.inject_chunk(token, h.k_pages, h.v_pages)
        return self.inject_commit(token)

    def inject_begin(self, meta: Dict[str, Any]) -> int:
        """First leg of the staged (chunked) inject: validate the
        handoff manifest — everything :meth:`inject_prefilled` checks,
        pages excluded — and reserve the sequence's worst-case blocks.
        Returns a staging token for :meth:`inject_chunk` /
        :meth:`inject_commit` / :meth:`inject_abort`. Until commit the
        staged sequence is invisible to decode, admission counts, and
        results — an abort (or a dropped peer connection mid-stream)
        simply returns the reservation, which is what makes a
        mid-transfer reset resolve exactly-once at the router."""
        if meta["block_size"] != self.cfg.block_size:
            raise ValueError(
                f"handoff block_size {meta['block_size']} != engine "
                f"block_size {self.cfg.block_size} — replicas must "
                "share geometry for pages to map block-for-block")
        plen = len(meta["prompt"])
        n_cached = int(meta["n_cached"])
        if not (plen <= n_cached <= plen + meta["max_new"]
                and meta["generated"]
                and n_cached == plen + len(meta["generated"]) - 1):
            raise ValueError(
                f"inconsistent handoff: n_cached={n_cached} "
                f"prompt={plen} generated={len(meta['generated'])}")
        n_page = int(meta["n_pages"])
        if n_page != self.allocator.blocks_for_tokens(n_cached):
            raise ValueError(
                f"handoff carries {n_page} pages but n_cached="
                f"{n_cached} needs "
                f"{self.allocator.blocks_for_tokens(n_cached)}")
        if len(self._active) + len(self._prefilling) >= self.cfg.max_batch:
            raise QueueFull("no batch slot for handoff",
                            reason="no_batch_slot",
                            retry_after_s=self._retry_after())
        need = self.allocator.blocks_for_tokens(plen + meta["max_new"])
        blocks = self.allocator.alloc(need)
        token = next(self._inject_tokens)
        self._inject_staging[token] = {
            "meta": meta, "blocks": blocks, "n_pages": n_page,
            "cursor": 0}
        return token

    def inject_chunk(self, token: int, k_pages, v_pages) -> int:
        """Scatter one block-aligned run of pages (``[cursor, cursor +
        chunk)`` in manifest page order) into the reserved blocks.
        Jitted donated scatter: pages land in place, O(carried pages),
        never a full-pool copy. The pad width rides the prefill bucket
        menu extended by table_width (a migrated RUNNING sequence can
        exceed the largest prompt bucket): one compiled program per
        width, device transfer proportional to the carried pages,
        NULL_BLOCK targets + zero pages for the padding rows — written
        garbage on the null block is never read, the prefill
        bucket-padding contract. Chunks target disjoint block rows, so
        the committed pool state is bitwise the monolithic scatter's
        regardless of chunking. Returns pages remaining."""
        st = self._inject_staging[token]
        k_pages = np.asarray(k_pages)
        v_pages = np.asarray(v_pages)
        cn = int(k_pages.shape[1])
        if cn < 1 or st["cursor"] + cn > st["n_pages"]:
            raise ValueError(
                f"inject chunk of {cn} pages at cursor {st['cursor']} "
                f"overruns the {st['n_pages']}-page manifest")
        width = pick_bucket(cn, self._inject_widths)
        if cn == width:
            # Bucket-exact chunk: no padding rows, no staging copy —
            # the wire arrays feed the scatter directly. This is the
            # shape a topology plan aims for (chunk sizes drawn from
            # the bucket menu), and it halves the inject's host-side
            # memory traffic.
            idx = np.asarray(
                st["blocks"][st["cursor"]:st["cursor"] + cn], np.int32)
            k_pad, v_pad = k_pages, v_pages
        else:
            idx = np.full(width, 0, np.int32)           # NULL_BLOCK
            idx[:cn] = st["blocks"][st["cursor"]:st["cursor"] + cn]
            shape = (k_pages.shape[0], width) + k_pages.shape[2:]
            k_pad = np.zeros(shape, k_pages.dtype)
            v_pad = np.zeros(shape, v_pages.dtype)
            k_pad[:, :cn] = k_pages
            v_pad[:, :cn] = v_pages
        self.cache.k, self.cache.v = self._inject_fn(
            self.cache.k, self.cache.v, idx, k_pad, v_pad)
        st["cursor"] += cn
        return st["n_pages"] - st["cursor"]

    def inject_commit(self, token: int) -> int:
        """Every manifest page landed: materialize the sequence into
        the decode batch and return its rid. Registration, metrics,
        and batch membership all happen HERE — a partially-streamed
        sequence never observes any of them."""
        st = self._inject_staging[token]
        if st["cursor"] != st["n_pages"]:
            raise ValueError(
                f"inject commit with {st['cursor']}/{st['n_pages']} "
                "pages streamed")
        del self._inject_staging[token]
        meta, blocks = st["meta"], st["blocks"]
        table = np.zeros(self._table_width, np.int32)
        table[:len(blocks)] = blocks
        rid = next(self._rids)
        seq = _Seq(
            rid=rid, prompt=list(meta["prompt"]),
            max_new=meta["max_new"], blocks=blocks, table=table,
            n_cached=int(meta["n_cached"]),
            generated=list(meta["generated"]),
            submitted_at=meta["submitted_at"],
            chain=list(meta["chain"]), registered=0,
            deadline_class=meta["deadline_class"],
            trace=int(meta.get("trace_id", 0)))
        seq.first_token_at = meta["first_token_at"]
        if self.cfg.prefix_caching:
            # Publish the injected prompt blocks locally: future
            # same-prefix requests (or handoffs) landing here hit them
            # for free. A hash already published keeps this private
            # copy anonymous (register no-ops), same as the twin race.
            for i, ch in enumerate(meta["chain"]):
                self.allocator.register(blocks[i], ch)
            seq.registered = len(meta["chain"])
        self._active.append(seq)
        self.metrics.record_handoff_in()
        return rid

    def inject_abort(self, token: int) -> None:
        """Discard a staged inject (stream died mid-transfer, or the
        source declared the manifest stale): the block reservation
        returns to the pool, any pages already scattered stay as
        unreferenced garbage on freed blocks — never attended to, the
        same contract as any freed block's stale contents. Idempotent
        per token."""
        st = self._inject_staging.pop(token, None)
        if st is not None:
            self.allocator.free(st["blocks"])

    def _decode_once(self) -> None:
        import jax

        if not self._active:
            return
        if self._spec is not None:
            # Speculative iteration: k draft proposals per sequence,
            # one chunked target verify, host-side greedy acceptance
            # with cursor-only rollback of rejected positions. Swaps
            # ONLY this decode iteration — admission, prefill,
            # retirement, handoff all run unchanged above/below it.
            self._spec.round()
            return
        n = len(self._active)
        bucket = pick_bucket(n, self._batch_buckets)
        tokens = np.zeros(bucket, np.int32)
        positions = np.zeros(bucket, np.int32)
        tables = np.zeros((bucket, self._table_width), np.int32)
        for i, seq in enumerate(self._active):
            tokens[i] = seq.last_token
            positions[i] = seq.n_cached
            tables[i] = seq.table
        t0 = self._clock()
        with jax.profiler.TraceAnnotation("serve:decode"):
            kc, vc, out = self._decode_fn(
                self._params, self.cache.k, self.cache.v, tokens,
                positions, tables)
            out = np.asarray(out)  # host sync
        dur = self._clock() - t0
        self.cache.k, self.cache.v = kc, vc
        for i, seq in enumerate(self._active):
            seq.n_cached += 1
            seq.generated.append(int(out[i]))
        self.metrics.record_decode(
            t0, dur, n, self.cfg.max_batch,
            traces=[s.trace for s in self._active if s.trace])
