"""Continuous-batching inference engine.

The serving analog of the training runtime: one process drives the
whole mesh, and scheduling is **iteration-level** (Orca OSDI'22 /
vLLM): every :meth:`ServeEngine.step` retires sequences that finished
on the previous iteration, expires queued requests past their
deadline, admits new requests into the running batch (one prefill
each), then runs ONE decode iteration for everything active. New
requests join the running batch mid-flight and finished sequences
leave immediately — the batch never drains to admit, which is where
the throughput win over static batching comes from on mixed-length
traffic.

Admission control is two-layered:

* **queue backpressure** — :meth:`submit` raises :class:`QueueFull`
  (503-style) once ``max_queue`` requests are waiting;
* **KV backpressure** — a request is admitted only when the block
  pool can reserve its worst case (prompt + max_new_tokens), so a
  running sequence can never hit out-of-blocks mid-decode (no
  preemption/swapping tier yet; the reservation is the simple-and-
  safe policy and `high_water` tells you how much it costs).

Two throughput levers sit on top of the paged layout:

* **prefix caching** (``ServeConfig.prefix_caching``) — admission
  walks the prompt's chained block hashes against the allocator's
  content index; every leading whole block already cached is mapped
  straight into the new sequence's block table (one refcount, zero
  FLOPs) and only the unmatched suffix is prefilled. Full prompt
  blocks are published back to the index after they are written, so
  a fleet of requests sharing a system prompt pays its prefill once.
* **chunked prefill** (``ServeConfig.prefill_chunk``) — a long
  suffix is split into block-aligned chunks processed across
  successive :meth:`ServeEngine.step` iterations, interleaved with
  decode, so one long prompt no longer monopolizes an iteration and
  spikes every in-flight sequence's per-token latency. A chunking
  sequence holds all its reserved blocks but does not enter the
  decode batch until its prefill completes.

Deadlines are absolute engine-clock times by which a request must be
*admitted* (first token scheduled); stale requests are rejected with a
503-style result rather than burning prefill FLOPs on an answer
nobody is waiting for. The clock is injectable for tests.

Determinism: FIFO admission, stable batch-slot assignment, greedy
argmax in-jit — the same submission order always yields bitwise the
same tokens, which the parity test pins.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu.serve import decode as decode_lib
from horovod_tpu.serve.kv_cache import (
    BlockAllocator, block_hash, init_kv_cache, pick_bucket,
)
from horovod_tpu.serve.metrics import ServeMetrics


class QueueFull(RuntimeError):
    """Admission-queue backpressure — shed load upstream."""
    http_status = 503


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs (model shape lives in ``TransformerConfig``)."""

    max_batch: int = 8           # decode batch slots
    max_queue: int = 64          # admission queue depth (then 503)
    block_size: int = 16         # KV tokens per block
    n_blocks: Optional[int] = None   # pool size; default = worst case
    max_prompt: int = 512        # longest admissible prompt
    max_new_tokens: int = 128    # per-request generation cap
    eos_id: Optional[int] = None
    # Shape buckets (None = powers-of-two menus). Fewer buckets = fewer
    # compiles; more buckets = less padding waste.
    batch_buckets: Optional[Tuple[int, ...]] = None
    prefill_buckets: Optional[Tuple[int, ...]] = None
    # "continuous": iteration-level admission (the point of this
    # engine). "static": admit only into an empty batch — the
    # classical serve loop, kept as the benchmark baseline.
    scheduling: str = "continuous"
    cache_dtype: Any = None      # default: model dtype
    # Map whole-block prompt prefixes out of the content-addressed
    # block cache instead of recomputing them (hit rate shows up in
    # metrics as prefix_cache_hit_rate). Off = every prompt pays full
    # prefill FLOPs, the pre-cache behavior.
    prefix_caching: bool = True
    # Max prefill tokens processed per engine step (block-aligned).
    # None = unbounded: every admitted request's whole suffix
    # prefills in its admission step (monolithic prefill). Set to
    # bound the prefill work one step can absorb, so long prompts
    # stream in across iterations interleaved with decode.
    prefill_chunk: Optional[int] = None


@dataclasses.dataclass
class RequestResult:
    rid: int
    status: str                  # "ok" | "expired"
    http_status: int             # 200 | 503
    tokens: List[int]
    n_prompt: int
    submitted_at: float
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None

    @property
    def first_token_latency_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at


@dataclasses.dataclass
class _Queued:
    rid: int
    prompt: List[int]
    max_new: int
    deadline: Optional[float]
    submitted_at: float
    chain: List[bytes]           # content-hash chain, hashed once at
    #                              submit (not per admission retry)


@dataclasses.dataclass
class _Seq:
    rid: int
    prompt: List[int]
    max_new: int
    blocks: List[int]            # refs held: shared prefix + private
    table: np.ndarray            # [table_width] int32 physical block ids
    n_cached: int                # tokens currently in the KV cache
    generated: List[int]
    submitted_at: float
    chain: List[bytes]           # content-hash chain, one per full
    #                              prompt block (empty: caching off)
    registered: int              # prompt blocks published (or mapped
    #                              from the cache) so far
    first_token_at: Optional[float] = None
    last_prefill_tok: int = 0    # argmax of the newest chunk's last
    #                              real position; the first generated
    #                              token once prefill completes

    @property
    def last_token(self) -> int:
        return self.generated[-1]

    def finished(self, eos_id: Optional[int]) -> bool:
        return (len(self.generated) >= self.max_new
                or (eos_id is not None and self.last_token == eos_id))


def _pow2_menu(lo: int, hi: int) -> Tuple[int, ...]:
    out = []
    b = lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return tuple(out)


class ServeEngine:
    def __init__(self, model_cfg, params, serve_cfg: Optional[ServeConfig]
                 = None, mesh: Optional[Any] = None,
                 clock=time.perf_counter):
        cfg = serve_cfg or ServeConfig()
        if cfg.scheduling not in ("continuous", "static"):
            raise ValueError(f"unknown scheduling {cfg.scheduling!r}")
        self.model_cfg = model_cfg
        self.cfg = cfg
        self.mesh = mesh
        self._params = params
        self._clock = clock

        bs = cfg.block_size
        # Prompt buckets are whole blocks (prefill writes pages).
        max_prompt_padded = -(-cfg.max_prompt // bs) * bs
        self._prefill_buckets = cfg.prefill_buckets or _pow2_menu(
            bs, max_prompt_padded)
        self._batch_buckets = cfg.batch_buckets or _pow2_menu(
            1, cfg.max_batch)
        self._table_width = -(-(max_prompt_padded + cfg.max_new_tokens) // bs)
        # Fail at construction, not mid-step after blocks are already
        # reserved: every admissible request must fit a bucket, and
        # every bucket's pages must fit the block table.
        if any(b % bs for b in self._prefill_buckets):
            raise ValueError(
                f"prefill_buckets {self._prefill_buckets} must be "
                f"multiples of block_size {bs}")
        if max(self._prefill_buckets) // bs > self._table_width:
            raise ValueError(
                f"largest prefill bucket {max(self._prefill_buckets)} "
                f"needs {max(self._prefill_buckets) // bs} blocks but "
                f"the block table holds {self._table_width}")
        pick_bucket(cfg.max_prompt, self._prefill_buckets)
        pick_bucket(cfg.max_batch, self._batch_buckets)
        if cfg.prefill_chunk is not None:
            # Chunks must start block-aligned (the resume fn's page
            # writes are blockwise) and fit a bucket.
            if cfg.prefill_chunk < bs or cfg.prefill_chunk % bs:
                raise ValueError(
                    f"prefill_chunk {cfg.prefill_chunk} must be a "
                    f"positive multiple of block_size {bs}")
            pick_bucket(cfg.prefill_chunk, self._prefill_buckets)

        n_blocks = cfg.n_blocks
        if n_blocks is None:
            # Worst case: every batch slot holds a maximal sequence
            # (+1 for the reserved null block).
            n_blocks = cfg.max_batch * self._table_width + 1
        self.allocator = BlockAllocator(n_blocks, bs)
        self.cache = init_kv_cache(model_cfg, n_blocks, bs, mesh=mesh,
                                   dtype=cfg.cache_dtype)
        self._prefill_fn, self._resume_fn, self._decode_fn = \
            decode_lib.make_serve_fns(
                model_cfg, mesh, block_size=bs,
                table_width=self._table_width)

        self.metrics = ServeMetrics(clock=clock)
        self.metrics.attach_allocator(self.allocator)
        self._queue: collections.deque[_Queued] = collections.deque()
        self._active: List[_Seq] = []
        # Admitted sequences whose prefill has not completed: they
        # hold their block reservation and consume a batch slot, but
        # only join the decode batch once prefill finishes.
        self._prefilling: List[_Seq] = []
        self._results: Dict[int, RequestResult] = {}
        self._rids = itertools.count()

    # -- submission --------------------------------------------------

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None,
               deadline: Optional[float] = None) -> int:
        """Enqueue a request; returns its id. Raises :class:`QueueFull`
        when the admission queue is at capacity (backpressure) and
        ``ValueError`` on shapes the engine cannot ever serve."""
        prompt = list(prompt)
        max_new = (self.cfg.max_new_tokens if max_new_tokens is None
                   else max_new_tokens)
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.cfg.max_prompt:
            raise ValueError(
                f"prompt length {len(prompt)} > max_prompt "
                f"{self.cfg.max_prompt}")
        if not 1 <= max_new <= self.cfg.max_new_tokens:
            raise ValueError(
                f"max_new_tokens {max_new} outside [1, "
                f"{self.cfg.max_new_tokens}]")
        if len(prompt) + max_new > self.model_cfg.max_seq:
            raise ValueError(
                f"prompt+max_new {len(prompt) + max_new} > model max_seq "
                f"{self.model_cfg.max_seq}")
        need = self.allocator.blocks_for_tokens(len(prompt) + max_new)
        if need > self.allocator.n_blocks - 1:
            # Worst-case reservation exceeds the whole pool: admission
            # could never succeed and FIFO would starve every request
            # behind it — reject now, not never.
            raise ValueError(
                f"request needs {need} KV blocks worst-case but the pool "
                f"holds {self.allocator.n_blocks - 1}; raise n_blocks or "
                "lower max_new_tokens")
        if len(self._queue) >= self.cfg.max_queue:
            self.metrics.record_rejected()
            raise QueueFull(
                f"admission queue full ({self.cfg.max_queue} waiting)")
        rid = next(self._rids)
        chain = (self._hash_chain(prompt) if self.cfg.prefix_caching
                 else [])
        self._queue.append(_Queued(rid, prompt, max_new, deadline,
                                   self._clock(), chain))
        self.metrics.record_submitted()
        self.metrics.record_queue_depth(len(self._queue))
        return rid

    # -- results -----------------------------------------------------

    @property
    def pending(self) -> bool:
        return bool(self._queue or self._prefilling or self._active)

    def result(self, rid: int) -> Optional[RequestResult]:
        return self._results.get(rid)

    @property
    def results(self) -> Dict[int, RequestResult]:
        return dict(self._results)

    # -- the scheduler iteration ------------------------------------

    def step(self) -> None:
        """One iteration: retire → expire → admit → prefill chunk(s)
        → decode."""
        now = self._clock()
        self._retire_finished(now)
        self._expire_queued(now)
        self._admit(now)
        self._advance_prefills()
        self._decode_once()
        self.metrics.record_queue_depth(len(self._queue))

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        for _ in range(max_steps):
            if not self.pending:
                return
            self.step()
        raise RuntimeError(f"engine still busy after {max_steps} steps")

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: Optional[int] = None) -> List[List[int]]:
        """Convenience batch API: serve ``prompts`` to completion and
        return their generated token lists in order."""
        rids = [self.submit(p, max_new_tokens) for p in prompts]
        self.run_until_idle()
        return [self._results[r].tokens for r in rids]

    # -- internals ---------------------------------------------------

    def _finish(self, seq: _Seq, now: float) -> None:
        self.allocator.free(seq.blocks)
        self._results[seq.rid] = RequestResult(
            rid=seq.rid, status="ok", http_status=200,
            tokens=list(seq.generated), n_prompt=len(seq.prompt),
            submitted_at=seq.submitted_at,
            first_token_at=seq.first_token_at, finished_at=now)
        self.metrics.record_finished()

    def _retire_finished(self, now: float) -> None:
        still = []
        for seq in self._active:
            if seq.finished(self.cfg.eos_id):
                self._finish(seq, now)
            else:
                still.append(seq)
        self._active = still

    def _expire_queued(self, now: float) -> None:
        keep: collections.deque[_Queued] = collections.deque()
        for req in self._queue:
            if req.deadline is not None and now > req.deadline:
                self._results[req.rid] = RequestResult(
                    rid=req.rid, status="expired", http_status=503,
                    tokens=[], n_prompt=len(req.prompt),
                    submitted_at=req.submitted_at, finished_at=now)
                self.metrics.record_expired()
            else:
                keep.append(req)
        self._queue = keep

    def _hash_chain(self, prompt: List[int]) -> List[bytes]:
        """Chained content hash per full prompt block (the partial
        tail block, if any, stays private and unhashed)."""
        bs = self.cfg.block_size
        chain, h = [], b""
        for i in range(len(prompt) // bs):
            h = block_hash(h, prompt[i * bs:(i + 1) * bs])
            chain.append(h)
        return chain

    def _admit(self, now: float) -> None:
        batch_was_empty = not self._active and not self._prefilling
        while (self._queue and
               len(self._active) + len(self._prefilling)
               < self.cfg.max_batch):
            if self.cfg.scheduling == "static" and not batch_was_empty:
                # Baseline scheduler: wait for the whole batch to
                # drain before admitting again.
                return
            req = self._queue[0]
            plen = len(req.prompt)
            need = self.allocator.blocks_for_tokens(plen + req.max_new)
            # Walk the chain against the content index; every leading
            # whole block already cached maps into this sequence's
            # table with one refcount, zero FLOPs. Capped at plen-1
            # tokens: the final prompt token must run through the
            # model — its logits are the first generated token. The
            # first walk is a non-mutating peek: a blocked request
            # retries admission every step, and taking/releasing refs
            # here would inflate the hit counters and churn the LRU
            # order with reuse that never happened.
            matchable = req.chain[:(plen - 1) // self.cfg.block_size]
            n_match, n_revive = 0, 0
            for h in matchable:
                b = self.allocator.peek(h)
                if b is None:
                    break
                n_match += 1
                if self.allocator.refcount(b) == 0:
                    # Reviving a refcount-0 cached block consumes a
                    # unit of n_free just like a fresh allocation, so
                    # it must count against capacity — or an
                    # overcommitted pool passes this check and then
                    # blows OutOfBlocks mid-admission.
                    n_revive += 1
            if not self.allocator.can_alloc(need - n_match + n_revive):
                # KV backpressure (FIFO: no overtaking, so tail
                # latency stays predictable under load).
                return
            self._queue.popleft()
            # Commit: nothing mutated between peek and acquire, so
            # the same blocks resolve — and hits (plus the one
            # boundary miss) count once, for an admission that
            # actually happened.
            matched: List[int] = []
            for h in matchable:
                b = self.allocator.acquire_cached(h)
                if b is None:
                    break
                matched.append(b)
            assert len(matched) == n_match
            blocks = matched + self.allocator.alloc(need - n_match)
            table = np.zeros(self._table_width, np.int32)
            table[:len(blocks)] = blocks
            n_hit = len(matched) * self.cfg.block_size
            self.metrics.record_prefix_lookup(n_hit, plen - n_hit)
            self._prefilling.append(_Seq(
                rid=req.rid, prompt=req.prompt, max_new=req.max_new,
                blocks=blocks, table=table, n_cached=n_hit,
                generated=[], submitted_at=req.submitted_at,
                chain=req.chain, registered=len(matched)))

    def _advance_prefills(self) -> None:
        """Run prefill chunks FIFO across admitted-but-incomplete
        sequences, bounded per step by ``prefill_chunk`` tokens
        (always at least one chunk, so progress is guaranteed). With
        ``prefill_chunk=None`` every waiting suffix completes this
        step — the monolithic behavior."""
        budget = self.cfg.prefill_chunk
        spent = 0
        while self._prefilling and (budget is None or spent < budget):
            seq = self._prefilling[0]
            self._extend_prefix_match(seq)
            remaining = len(seq.prompt) - seq.n_cached
            if budget is None:
                chunk = remaining
            else:
                # Cap by the UNSPENT budget, not the full chunk size:
                # several queued suffixes could otherwise spend up to
                # 2N-1 tokens in one step. Non-final chunks must end
                # block-aligned (the next chunk's pages start there).
                chunk = min(remaining, budget - spent)
                if chunk < remaining:
                    chunk -= chunk % self.cfg.block_size
                    if chunk == 0:
                        break
            spent += self._run_prefill_chunk(seq, chunk)
            if seq.n_cached >= len(seq.prompt):
                self._prefilling.pop(0)
                self._complete_prefill(seq)

    def _extend_prefix_match(self, seq: _Seq) -> None:
        """Retry the cache walk just before prefilling. Admission in a
        burst step matches against a cache its same-step siblings
        haven't populated yet (they register at prefill, after the
        admission loop); by prefill time an identical prefix admitted
        one slot earlier IS published, so a second walk converts those
        would-be prefill tokens into hits. Safe whenever the cursor
        sits on a whole-block boundary with every block up to it
        published or mapped: the swapped slots hold no K/V yet, and
        the displaced private blocks return to the pool."""
        if (not self.cfg.prefix_caching
                or seq.n_cached != seq.registered * self.cfg.block_size):
            return
        plen = len(seq.prompt)
        extended = 0
        for i in range(seq.registered,
                       (plen - 1) // self.cfg.block_size):
            # peek first: this walk reruns at every block-aligned
            # chunk boundary, and a cold prompt would otherwise log
            # one spurious miss per chunk.
            if self.allocator.peek(seq.chain[i]) is None:
                break
            b = self.allocator.acquire_cached(seq.chain[i])
            if b is None:
                break
            self.allocator.free([seq.blocks[i]])
            seq.blocks[i] = b
            seq.table[i] = b
            seq.n_cached += self.cfg.block_size
            seq.registered += 1
            extended += self.cfg.block_size
        if extended:
            self.metrics.record_prefix_extend(extended)

    def _run_prefill_chunk(self, seq: _Seq, chunk: int) -> int:
        import jax

        plen = len(seq.prompt)
        offset = seq.n_cached
        toks = np.zeros(pick_bucket(chunk, self._prefill_buckets), np.int32)
        toks[:chunk] = seq.prompt[offset:offset + chunk]
        t0 = self._clock()
        with jax.profiler.TraceAnnotation("serve:prefill"):
            if offset == 0 and chunk == plen:
                # Whole cold prompt: the monolithic program (exactly
                # the pre-cache code path, and the cheaper attention —
                # prompt-local instead of a full table gather).
                kc, vc, tok = self._prefill_fn(
                    self._params, self.cache.k, self.cache.v, toks,
                    np.int32(plen), seq.table)
            else:
                kc, vc, tok = self._resume_fn(
                    self._params, self.cache.k, self.cache.v, toks,
                    np.int32(offset), np.int32(chunk), seq.table)
            tok = int(tok)  # host sync — the step is done when this is
        dur = self._clock() - t0
        self.cache.k, self.cache.v = kc, vc
        seq.n_cached = offset + chunk
        seq.last_prefill_tok = tok
        self.metrics.record_prefill(t0, dur, chunk, offset=offset)
        if self.cfg.prefix_caching:
            # Publish the prompt blocks this chunk filled. A losing
            # race (hash already published by a concurrent twin) keeps
            # the private copy anonymous — register() no-ops.
            n_full = seq.n_cached // self.cfg.block_size
            for i in range(seq.registered, n_full):
                self.allocator.register(seq.blocks[i], seq.chain[i])
            seq.registered = max(seq.registered, n_full)
        return chunk

    def _complete_prefill(self, seq: _Seq) -> None:
        now = self._clock()
        seq.generated.append(seq.last_prefill_tok)
        seq.first_token_at = now
        self.metrics.record_first_token(now - seq.submitted_at)
        if seq.finished(self.cfg.eos_id):
            self._finish(seq, now)
        else:
            self._active.append(seq)

    def _decode_once(self) -> None:
        import jax

        if not self._active:
            return
        n = len(self._active)
        bucket = pick_bucket(n, self._batch_buckets)
        tokens = np.zeros(bucket, np.int32)
        positions = np.zeros(bucket, np.int32)
        tables = np.zeros((bucket, self._table_width), np.int32)
        for i, seq in enumerate(self._active):
            tokens[i] = seq.last_token
            positions[i] = seq.n_cached
            tables[i] = seq.table
        t0 = self._clock()
        with jax.profiler.TraceAnnotation("serve:decode"):
            kc, vc, out = self._decode_fn(
                self._params, self.cache.k, self.cache.v, tokens,
                positions, tables)
            out = np.asarray(out)  # host sync
        dur = self._clock() - t0
        self.cache.k, self.cache.v = kc, vc
        for i, seq in enumerate(self._active):
            seq.n_cached += 1
            seq.generated.append(int(out[i]))
        self.metrics.record_decode(t0, dur, n, self.cfg.max_batch)
