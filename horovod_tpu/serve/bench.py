"""Serving benchmarks: scheduling, prefix caching, chunked prefill.

Three serving claims worth measuring (Orca/vLLM, and the MLPerf-pod
motivation of reporting tails next to throughput):

* **continuous vs static batching** on a mixed-length trace —
  iteration-level admission keeps the decode batch full while a
  static scheduler idles slots waiting for the batch's straggler.
  Both schedulers run the SAME jitted prefill/decode programs and the
  same KV pool; the ratio isolates the scheduling win.
* **prefix caching** on a shared-system-prompt trace
  (:func:`make_shared_prefix_trace`) — with the content-addressed
  block cache on, only each request's unique suffix pays prefill
  FLOPs; the ``serve_prefix_*`` keys report the cache-on/off
  throughput ratio, token hit rate, and that the decoded streams are
  identical.
* **chunked prefill** on the mixed trace — long prompts streamed in
  chunks between decode iterations must hold the per-token latency
  tail (``serve_chunked_p99_per_token_ms``) near the monolithic
  run's while matching its tokens.

Run directly (CPU-friendly):
    JAX_PLATFORMS=cpu python -m horovod_tpu.serve.bench
or let the repo-level ``bench.py`` fold the metrics into its round
payload (``serve_tokens_per_sec_per_chip``, ``serve_prefix_*``,
``serve_p99_first_token_ms``, ...).
"""

from __future__ import annotations

import json
import statistics
import time
from typing import List, Tuple

import numpy as np

from horovod_tpu.serve.metrics import percentile


def make_trace(n_requests: int = 40, *, seed: int = 0,
               min_prompt: int = 4, max_prompt: int = 32,
               min_new: int = 8, max_new: int = 64,
               vocab: int = 256) -> List[Tuple[List[int], int]]:
    """Deterministic mixed-length request trace:
    ``[(prompt_tokens, max_new_tokens), ...]``."""
    rng = np.random.RandomState(seed)
    # Callers shrink max_* freely (e.g. a tiny-model demo); the lower
    # bounds follow rather than erroring on an empty range.
    min_prompt = min(min_prompt, max_prompt)
    min_new = min(min_new, max_new)
    trace = []
    for _ in range(n_requests):
        plen = int(rng.randint(min_prompt, max_prompt + 1))
        nnew = int(rng.randint(min_new, max_new + 1))
        prompt = rng.randint(1, vocab, size=plen).astype(np.int32).tolist()
        trace.append((prompt, nnew))
    return trace


def make_shared_prefix_trace(n_requests: int = 32, *, seed: int = 0,
                             prefix_len: int = 64, min_suffix: int = 4,
                             max_suffix: int = 12, min_new: int = 4,
                             max_new: int = 8, vocab: int = 256,
                             ) -> List[Tuple[List[int], int]]:
    """Deterministic multi-tenant-style trace: every request shares one
    ``prefix_len``-token system prompt and appends a short unique
    suffix — the regime where block-level prefix reuse pays (thousands
    of requests, one shared preamble)."""
    rng = np.random.RandomState(seed)
    prefix = rng.randint(1, vocab, size=prefix_len).astype(np.int32).tolist()
    trace = []
    for _ in range(n_requests):
        slen = int(rng.randint(min_suffix, max_suffix + 1))
        nnew = int(rng.randint(min_new, max_new + 1))
        suffix = rng.randint(1, vocab, size=slen).astype(np.int32).tolist()
        trace.append((prefix + suffix, nnew))
    return trace


def make_multi_tenant_trace(n_requests: int = 48, *, seed: int = 0,
                            n_tenants: int = 8, prefix_len: int = 32,
                            min_suffix: int = 2, max_suffix: int = 8,
                            min_new: int = 2, max_new: int = 4,
                            vocab: int = 256,
                            ) -> List[Tuple[List[int], int]]:
    """Deterministic fleet-routing trace: ``n_tenants`` distinct
    system prompts, requests interleaved across tenants. This is the
    regime where PLACEMENT (not just caching) decides the hit rate:
    affinity keeps each tenant's prefix hot on one replica, while
    random placement re-prefills it on every replica it scatters to."""
    rng = np.random.RandomState(seed)
    prefixes = [rng.randint(1, vocab, size=prefix_len).astype(
        np.int32).tolist() for _ in range(n_tenants)]
    trace = []
    for _ in range(n_requests):
        t = int(rng.randint(n_tenants))
        slen = int(rng.randint(min_suffix, max_suffix + 1))
        nnew = int(rng.randint(min_new, max_new + 1))
        suffix = rng.randint(1, vocab, size=slen).astype(np.int32).tolist()
        trace.append((prefixes[t] + suffix, nnew))
    return trace


def _run_trace(engine, trace) -> dict:
    """Submit the whole trace up front (closed-loop burst — worst case
    for admission) and serve to completion; returns the engine metrics
    snapshot plus wall-clock throughput. ``_tokens`` carries the
    decoded streams in submission order (for parity checks; callers
    pop it before emitting JSON)."""
    t0 = time.perf_counter()
    engine.metrics.reset()
    rids = [engine.submit(p, n) for p, n in trace]
    engine.run_until_idle()
    dt = time.perf_counter() - t0
    streams = [engine.result(r).tokens for r in rids]
    total = sum(len(s) for s in streams)
    snap = engine.metrics.snapshot()
    snap["wall_s"] = round(dt, 3)
    snap["tokens_total"] = total
    snap["tokens_per_sec_wall"] = round(total / dt, 2)
    snap["_tokens"] = streams
    snap["_per_token_s"] = list(engine.metrics.per_token_s)
    return snap


def _interleaved_passes(engines, trace, repeats: int, warmup: bool) -> dict:
    """Shared measurement protocol for the serve benchmarks: warm
    every engine on the trace (compiles all buckets; populates any
    prefix cache), then run measured passes INTERLEAVED round-robin
    across arms — on a timeshared host, sequential per-arm blocks
    drift +-30% apart under scheduler interference, which is noise in
    exactly the ratios these benchmarks report. Returns
    ``{label: [pass snapshots]}``."""
    if warmup:
        for engine in engines.values():
            _run_trace(engine, trace)
    passes = {label: [] for label in engines}
    for _ in range(max(repeats, 1)):
        for label, engine in engines.items():
            passes[label].append(_run_trace(engine, trace))
    return passes


def _best_pass(snaps) -> dict:
    return dict(max(snaps, key=lambda s: s["tokens_per_sec_wall"]))


def run_serving_benchmark(n_requests: int = 40, *, seed: int = 0,
                          model_cfg=None, max_batch: int = 8,
                          block_size: int = 8, warmup: bool = True,
                          repeats: int = 3,
                          prefill_chunk: int = 16) -> dict:
    """Measure continuous vs static batching throughput and latency
    tails on the same mixed-length trace. Returns the flat metric dict
    the repo benchmark folds into its payload.

    Each scheduler is measured ``repeats`` times and the best pass
    wins (the busbw protocol's rationale: on a timeshared host a
    single pass can eat scheduler interference that has nothing to do
    with the engine; the least-interfered pass is the comparable one).
    """
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models import TransformerConfig, init_transformer
    from horovod_tpu.serve.engine import ServeConfig, ServeEngine

    if model_cfg is None:
        # f32 tiny shape: CPU-fast, and the benchmark isolates
        # scheduling, not matmul throughput.
        model_cfg = TransformerConfig.tiny(dtype=jnp.float32, remat=False)
    params = init_transformer(model_cfg, jax.random.PRNGKey(0))
    trace = make_trace(n_requests, seed=seed)
    max_prompt = max(len(p) for p, _ in trace)
    max_new = max(n for _, n in trace)
    n_dev = jax.device_count()

    engines = {}
    for label, overrides in (
            ("continuous", {}),
            ("static", {"scheduling": "static"}),
            # The same iteration-level scheduler with long prompts
            # streamed in `prefill_chunk`-token chunks between decode
            # iterations — the latency-protection mode. Measured on
            # the same trace so its per-token tail is directly
            # comparable to the monolithic-prefill run.
            ("chunked", {"prefill_chunk": prefill_chunk})):
        # prefix_caching OFF for all scheduling arms: the measured
        # passes replay the warmup's prompts, so a warm cache would
        # shrink every prefill to ~one token — the chunked arm would
        # stop exercising chunked prefill, and the serve_* keys would
        # stop comparing against the cache-free earlier rounds. The
        # cache gets its own controlled benchmark below.
        cfg = ServeConfig(
            max_batch=max_batch, max_queue=max(len(trace), 8),
            block_size=block_size, max_prompt=max_prompt,
            max_new_tokens=max_new, prefix_caching=False, **overrides)
        engines[label] = ServeEngine(model_cfg, params, cfg)
    # Latency tails are computed over the POOLED samples of all of an
    # arm's passes (not the best pass alone): a per-pass p99 over
    # ~200 decode samples is a 2nd-worst-sample order statistic that
    # one scheduler hiccup owns, while interleaving spreads hiccups
    # evenly across arms, so the pooled tails are comparable.
    # First-token keys take the min across passes (least-interfered).
    passes = _interleaved_passes(engines, trace, repeats, warmup)
    snaps = {label: _best_pass(ps) for label, ps in passes.items()}
    for label, ps in passes.items():
        pooled = [x for s in ps for x in s["_per_token_s"]]
        for q in (50, 99):
            v = percentile(pooled, q)
            snaps[label][f"p{q}_per_token_ms"] = (
                None if v is None else round(v * 1e3, 3))
        for k in ("p50_first_token_ms", "p99_first_token_ms"):
            vals = [s[k] for s in ps if s[k] is not None]
            snaps[label][k] = min(vals) if vals else None

    cont, stat, chk = snaps["continuous"], snaps["static"], snaps["chunked"]
    ratio = (cont["tokens_per_sec_wall"] / stat["tokens_per_sec_wall"]
             if stat["tokens_per_sec_wall"] else None)
    return {
        "serve_tokens_per_sec_per_chip":
            round(cont["tokens_per_sec_wall"] / n_dev, 2),
        "serve_static_tokens_per_sec_per_chip":
            round(stat["tokens_per_sec_wall"] / n_dev, 2),
        "serve_continuous_over_static":
            None if ratio is None else round(ratio, 3),
        "serve_p50_first_token_ms": cont["p50_first_token_ms"],
        "serve_p99_first_token_ms": cont["p99_first_token_ms"],
        "serve_p50_per_token_ms": cont["p50_per_token_ms"],
        "serve_p99_per_token_ms": cont["p99_per_token_ms"],
        "serve_batch_occupancy": cont["batch_occupancy"],
        "serve_static_batch_occupancy": stat["batch_occupancy"],
        "serve_decode_steps": cont["decode_steps"],
        "serve_static_decode_steps": stat["decode_steps"],
        "serve_chunked_tokens_per_sec_per_chip":
            round(chk["tokens_per_sec_wall"] / n_dev, 2),
        "serve_chunked_p50_per_token_ms": chk["p50_per_token_ms"],
        "serve_chunked_p99_per_token_ms": chk["p99_per_token_ms"],
        "serve_chunked_p99_first_token_ms": chk["p99_first_token_ms"],
        "serve_chunked_tokens_identical":
            chk["_tokens"] == cont["_tokens"],
    }


def run_prefix_benchmark(n_requests: int = 32, *, seed: int = 0,
                         model_cfg=None, max_batch: int = 8,
                         block_size: int = 8, prefix_len: int = 64,
                         warmup: bool = True, repeats: int = 3) -> dict:
    """Measure the prefix-cache win on the shared-system-prompt trace:
    the same engine geometry served with the content-addressed cache
    on vs off (`ServeConfig.prefix_caching`), best-of-``repeats``
    each. The cache-on run should beat cache-off on tokens/sec (only
    unmatched suffixes pay prefill FLOPs) with an identical decoded
    stream — both are asserted by the slow tier test and reported in
    the payload."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models import TransformerConfig, init_transformer
    from horovod_tpu.serve.engine import ServeConfig, ServeEngine

    if model_cfg is None:
        # NOT the scheduling benchmark's CI-scaffold tiny shape: at
        # d=64 every jitted call costs ~0.5 ms of dispatch no matter
        # the token count, so skipping 64 of 70 prefill tokens moves
        # wall time by noise. d=256 makes prefill FLOPs the cost the
        # cache actually removes while keeping compile+warmup ~5 s.
        model_cfg = TransformerConfig.tiny(
            d_model=256, d_ff=1024, n_layers=2, n_heads=8, n_kv_heads=4,
            dtype=jnp.float32, remat=False)
    params = init_transformer(model_cfg, jax.random.PRNGKey(0))
    # Short decodes: the cache claim is about *prompt* FLOPs, and each
    # generated token adds identical decode cost to both arms,
    # diluting the measured ratio toward 1.
    trace = make_shared_prefix_trace(n_requests, seed=seed,
                                     prefix_len=prefix_len,
                                     min_new=2, max_new=4)
    max_prompt = max(len(p) for p, _ in trace)
    max_new = max(n for _, n in trace)
    n_dev = jax.device_count()
    # Pool = worst-case live reservation PLUS cache headroom (the
    # shared prefix + one unique tail block per request). The default
    # worst-case-only sizing leaves refcount-0 cached blocks first in
    # line for eviction whenever admission reserves a full wave, which
    # silently degrades the cache exactly when the engine is busy —
    # the provisioning rule docs/serving.md spells out.
    blocks_per_seq = -(-(-(-max_prompt // block_size) * block_size
                         + max_new) // block_size)
    n_blocks = (max_batch * blocks_per_seq
                + prefix_len // block_size + n_requests + 1)

    engines = {}
    for label, caching in (("cache", True), ("nocache", False)):
        cfg = ServeConfig(
            max_batch=max_batch, max_queue=max(len(trace), 8),
            block_size=block_size, max_prompt=max_prompt,
            max_new_tokens=max_new, n_blocks=n_blocks,
            prefix_caching=caching)
        engines[label] = ServeEngine(model_cfg, params, cfg)
    # The warmup pass compiles every bucket AND (cache-on arm)
    # populates the prefix index, so the measured passes time
    # steady-state serving with a warm cache — the regime the cache
    # exists for, and exactly the variable this benchmark isolates.
    passes = _interleaved_passes(engines, trace, repeats, warmup)
    snaps = {label: _best_pass(ps) for label, ps in passes.items()}

    hit, miss = snaps["cache"], snaps["nocache"]
    speedup = (hit["tokens_per_sec_wall"] / miss["tokens_per_sec_wall"]
               if miss["tokens_per_sec_wall"] else None)
    return {
        "serve_prefix_tokens_per_sec_per_chip":
            round(hit["tokens_per_sec_wall"] / n_dev, 2),
        "serve_prefix_nocache_tokens_per_sec_per_chip":
            round(miss["tokens_per_sec_wall"] / n_dev, 2),
        "serve_prefix_cache_speedup":
            None if speedup is None else round(speedup, 3),
        "serve_prefix_cache_hit_rate": hit["prefix_cache_hit_rate"],
        "serve_prefix_p99_first_token_ms": hit["p99_first_token_ms"],
        "serve_prefix_nocache_p99_first_token_ms":
            miss["p99_first_token_ms"],
        "serve_prefix_block_evictions": hit["prefix_block_evictions"],
        "serve_prefix_kv_high_water": hit["kv_blocks_high_water"],
        "serve_prefix_tokens_identical": hit["_tokens"] == miss["_tokens"],
    }


def run_spec_benchmark(n_requests: int = 24, *, seed: int = 0,
                       draft_cfg=None, target_layers: int = 8,
                       spec_k: int = 4, max_batch: int = 4,
                       block_size: int = 8, warmup: bool = True,
                       repeats: int = 3) -> dict:
    """The speculative-decoding claim: on a decode-heavy multi-tenant
    trace, a draft/target pair beats plain decode on tokens/sec at
    equal-or-better p99 first-token, with bitwise-identical streams
    (greedy acceptance) and the accept rate reported.

    The pair is the **idealized construction**
    (:func:`~horovod_tpu.serve.speculative.make_draft_target_params`):
    the target is ``target_layers`` deep but its extra layers have
    zeroed residual out-projections, so it computes the 1-layer
    draft's exact logits — accept rate 1.0 by construction. That
    isolates the mechanism under measurement: per accepted token the
    target's weights stream once per ``spec_k`` tokens instead of once
    per token (decode is weight-bound at small batch — on CPU exactly
    as on TPU), while the verify chunk reuses one weight pass for the
    whole chunk. A real draft scales the win by its measured accept
    rate, which is why the rate rides the payload next to the ratio.

    Arms are interleaved per the +-30% protocol; throughput takes the
    best pass, first-token tails the least-interfered (min) pass,
    accept rate pools token counts across passes."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models import TransformerConfig
    from horovod_tpu.serve.engine import ServeConfig, ServeEngine
    from horovod_tpu.serve.speculative import (
        DraftConfig, make_draft_target_params,
    )

    if draft_cfg is None:
        # d=512 x 8 target layers so the per-call cost is the weight
        # pass, not dispatch (at d<=256 on this host the ~1.5ms jit
        # dispatch dominates and speculation's k+1 calls per k tokens
        # measure call-count, not the mechanism; decode on real
        # hardware is weight-bound, which is the regime this isolates).
        draft_cfg = TransformerConfig.tiny(
            d_model=512, d_ff=2048, n_layers=1, n_heads=8, n_kv_heads=4,
            dtype=jnp.float32, remat=False)
    target_cfg, target_params = make_draft_target_params(
        draft_cfg, n_layers=target_layers, seed=0)
    # Decode-heavy trace: speculation pays per GENERATED token, so the
    # mixed-tenant prompts stay short and the decodes run long.
    trace = make_multi_tenant_trace(n_requests, seed=seed, min_new=6,
                                    max_new=12)
    max_prompt = max(len(p) for p, _ in trace)
    max_new = max(n for _, n in trace)
    base = dict(max_batch=max_batch, max_queue=max(len(trace), 8),
                block_size=block_size, max_prompt=max_prompt,
                max_new_tokens=max_new)
    engines = {
        "plain": ServeEngine(target_cfg, target_params,
                             ServeConfig(**base)),
        "spec": ServeEngine(target_cfg, target_params, ServeConfig(
            **base, draft=DraftConfig(draft_cfg, seed=0),
            spec_k=spec_k)),
    }
    passes = _interleaved_passes(engines, trace, repeats, warmup)
    snaps = {label: _best_pass(ps) for label, ps in passes.items()}
    for label, ps in passes.items():
        vals = [s["p99_first_token_ms"] for s in ps
                if s["p99_first_token_ms"] is not None]
        snaps[label]["p99_first_token_ms"] = min(vals) if vals else None
    proposed = sum(s["spec_proposed_total"] for s in passes["spec"])
    accepted = sum(s["spec_accepted_total"] for s in passes["spec"])
    ref = snaps["plain"]["_tokens"]
    identical = all(s["_tokens"] == ref
                    for ps in passes.values() for s in ps)
    plain_tps = snaps["plain"]["tokens_per_sec_wall"]
    spec_tps = snaps["spec"]["tokens_per_sec_wall"]
    return {
        "serve_spec_tokens_per_sec": spec_tps,
        "serve_spec_plain_tokens_per_sec": plain_tps,
        "serve_spec_over_plain": (round(spec_tps / plain_tps, 3)
                                  if plain_tps else None),
        "serve_spec_accept_rate": (round(accepted / proposed, 4)
                                   if proposed else 0.0),
        "serve_spec_p99_first_token_ms":
            snaps["spec"]["p99_first_token_ms"],
        "serve_spec_plain_p99_first_token_ms":
            snaps["plain"]["p99_first_token_ms"],
        "serve_spec_verify_rounds_count": snaps["spec"]["spec_rounds"],
        "serve_spec_tokens_identical": identical,
    }


def _thread_fleet(n: int):
    """In-thread remote fleet: every replica is a real ReplicaWorker
    dispatch loop on a daemon thread over a socketpair — the RPC seam
    without process spawn, sharing this process's jit memo. The
    workers' peer bulk listeners are REAL loopback TCP sockets, so the
    direct migration plane is exercised end to end."""
    import socket
    import threading

    from horovod_tpu.serve.rpc import RpcConn, WorkerHandle
    from horovod_tpu.serve.worker import ReplicaWorker

    handles = []
    for _ in range(n):
        a, b = socket.socketpair()
        w = ReplicaWorker(RpcConn(b))
        threading.Thread(target=w.serve, daemon=True).start()
        handles.append(WorkerHandle(conn=RpcConn(a)))
    return handles


def _run_router_pass(model_cfg, params, trace, *, placement: str,
                     n_replicas: int, n_prefill: int, serve_cfg,
                     seed: int, workers=None,
                     handoff_compression=None,
                     direct_migration: str = "env") -> dict:
    """One cold-fleet pass: fresh router (empty caches, reset
    placement state) over the whole trace. Freshness is the point —
    the routed-vs-random claim is about where PLACEMENT puts the
    first prefill of each tenant prefix, which a warm cache would
    erase. The jitted programs are memoized on the shared geometry,
    so only the first-ever pass pays compiles.

    ``workers`` lifts the pass cross-process: the same spawned worker
    handles are re-configured into a fresh fleet each pass (cold KV
    pools, warm per-process jit caches — the cross-process twin of the
    memo), and the spans moved over RPC are tallied into
    ``handoff_wire_bytes`` / ``handoff_raw_bytes`` deltas."""
    from horovod_tpu.serve.router import RouterConfig, ServeRouter

    rc = RouterConfig(n_replicas=n_replicas, n_prefill=n_prefill,
                      max_queue=max(len(trace), 8),
                      placement=placement, seed=seed,
                      handoff_compression=handoff_compression,
                      direct_migration=direct_migration)
    router = ServeRouter(model_cfg, None if workers else params, rc,
                         serve_cfg, workers=workers, worker_seed=0)
    wire0 = sum(w.conn.span_wire_bytes for w in workers or [])
    raw0 = sum(w.conn.span_raw_bytes for w in workers or [])
    t0 = time.perf_counter()
    rids = [router.submit(p, n) for p, n in trace]
    router.run_until_idle()
    dt = time.perf_counter() - t0
    streams = [router.result(r).tokens for r in rids]
    total = sum(len(s) for s in streams)
    snap = router.metrics.snapshot()
    return {
        "wall_s": dt,
        "tokens_per_sec_wall": round(total / dt, 2),
        "hit_tokens": snap["prefix_hit_tokens"],
        "prefill_tokens": snap["prefix_prefill_tokens"],
        "handoffs": snap["handoffs"],
        "first_token_s": [x for e in router.engines
                          for x in e.metrics.first_token_s],
        "handoff_wire_bytes":
            sum(w.conn.span_wire_bytes for w in workers or []) - wire0,
        "handoff_raw_bytes":
            sum(w.conn.span_raw_bytes for w in workers or []) - raw0,
        "p50_migration_ms": snap["p50_migration_ms"],
        "migration_bytes": snap["migration_bytes_total"],
        "direct_migrations": snap["direct_migrations_total"],
        "_tokens": streams,
    }


def run_router_benchmark(n_requests: int = 48, *, seed: int = 0,
                         model_cfg=None, n_replicas: int = 4,
                         max_batch: int = 4, block_size: int = 8,
                         n_tenants: int = 8, prefix_len: int = 32,
                         warmup: bool = True, repeats: int = 3,
                         cross_process: bool = False) -> dict:
    """The fleet-router claim: on a multi-tenant shared-prefix trace
    replayed at ``n_replicas`` replicas, cache-affinity placement
    beats random placement on prefix hit rate AND p99 first-token
    latency, with token streams bitwise identical to a single replica
    — including through the prefill/decode handoff (a split fleet
    serves the same trace as a parity arm).

    Protocol: each measured pass runs a FRESH cold fleet (placement
    decides who pays each tenant's first prefill), arms interleaved
    round-robin per the +-30% drift protocol (docs/perf_tuning.md);
    throughput keys take the best pass, latency tails pool samples
    across every pass of an arm, hit rates pool token counts (they
    are deterministic per arm up to admission timing).

    ``cross_process=True`` adds the RPC arm (ISSUE 11): the same
    routed fleet with every replica a spawned worker process,
    interleaved with the in-process passes so the reported
    ``serve_router_rpc_over_inproc`` ratio — the RPC tax — compares
    like weather with like. A split cross-process pass with bf16 KV
    encoding additionally reports the handoff bytes the codec saves
    (``serve_router_rpc_handoff_bytes_saved_pct``)."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models import TransformerConfig, init_transformer
    from horovod_tpu.serve.engine import ServeConfig, ServeEngine

    if model_cfg is None:
        # Same rationale as the prefix benchmark: the d=64 scaffold is
        # dispatch-bound, so skipped prefill FLOPs vanish into noise;
        # d=256 makes the prefill work routing avoids actually show up
        # in wall time and first-token latency.
        model_cfg = TransformerConfig.tiny(
            d_model=256, d_ff=1024, n_layers=2, n_heads=8, n_kv_heads=4,
            dtype=jnp.float32, remat=False)
    params = init_transformer(model_cfg, jax.random.PRNGKey(0))
    trace = make_multi_tenant_trace(
        n_requests, seed=seed, n_tenants=n_tenants,
        prefix_len=prefix_len, min_new=2, max_new=4)
    max_prompt = max(len(p) for p, _ in trace)
    max_new = max(n for _, n in trace)
    n_dev = jax.device_count()
    # Per-replica pool: worst-case live reservation + cache headroom
    # for every tenant prefix plus the unique tails (docs/serving.md
    # provisioning rule) — the benchmark isolates placement, not
    # eviction pressure.
    blocks_per_seq = -(-(-(-max_prompt // block_size) * block_size
                         + max_new) // block_size)
    n_blocks = (max_batch * blocks_per_seq
                + n_tenants * (prefix_len // block_size)
                + n_requests + 1)
    serve_cfg = ServeConfig(
        max_batch=max_batch, max_queue=max(n_requests, 8),
        block_size=block_size, max_prompt=max_prompt,
        max_new_tokens=max_new, n_blocks=n_blocks)

    def routed_pass():
        return _run_router_pass(
            model_cfg, params, trace, placement="affinity",
            n_replicas=n_replicas, n_prefill=0, serve_cfg=serve_cfg,
            seed=seed)

    def random_pass():
        return _run_router_pass(
            model_cfg, params, trace, placement="random",
            n_replicas=n_replicas, n_prefill=0, serve_cfg=serve_cfg,
            seed=seed)

    handles = []
    if cross_process:
        from horovod_tpu.serve.rpc import spawn_worker
        handles = [spawn_worker() for _ in range(n_replicas)]

    def rpc_pass(n_prefill=0, compression=None):
        return _run_router_pass(
            model_cfg, params, trace, placement="affinity",
            n_replicas=n_replicas, n_prefill=n_prefill,
            serve_cfg=serve_cfg, seed=seed, workers=handles,
            handoff_compression=compression)

    try:
        if warmup:
            routed_pass()      # compiles every bucket once
            if cross_process:
                rpc_pass()     # ...and once per worker process
        passes = {"routed": [], "random": []}
        if cross_process:
            passes["rpc"] = []
        for _ in range(max(repeats, 1)):
            passes["routed"].append(routed_pass())
            passes["random"].append(random_pass())
            if cross_process:
                passes["rpc"].append(rpc_pass())
        rpc_split = (rpc_pass(n_prefill=max(n_replicas // 2, 1),
                              compression="bf16")
                     if cross_process else None)
    finally:
        for h in handles:
            h.close()

    # Direct-vs-relayed migration arm (docs/serving.md "Direct
    # migration"): a split prefill/decode fleet of in-thread remote
    # workers with bf16 KV encoding, so EVERY request migrates its
    # pages pool to pool. The direct arm streams worker->worker over
    # the peer bulk channel; the relayed arm forces the router-hop
    # path (HOROVOD_FLEET_DIRECT_MIGRATION=off semantics). Fresh fleet
    # per pass (cold pools — migration time is the claim, not cache
    # reuse), arms interleaved per the +-30% drift protocol, p50 takes
    # the best pass. Byte savings compare the direct arm's wire bytes
    # (one bf16 traversal) against the relayed arm's router-held raw
    # bytes — the two traversals the direct plane deletes.
    #
    # The arm carries its own long-context trace: at the router
    # trace's ~100KB sequences, fixed per-move dispatch (the jitted
    # inject scatter, RPC marshalling) drowns the traversal the
    # direct plane deletes; ~2MB sequences put the claim where
    # production KV sizes live.
    mig_cfg = TransformerConfig.tiny(
        d_model=256, d_ff=1024, n_layers=2, n_heads=8, n_kv_heads=4,
        dtype=jnp.float32, remat=False, max_seq=1024)
    # The prompt lands the cached stream on EXACTLY 128 pages — the
    # 1024-token bucket width — so the bucket-exact gather/scatter
    # (no padding rows, no staging copy) runs on both arms.
    mig_prompt = 128 * block_size - 2
    rng = np.random.RandomState(seed)
    # 16 moves per pass: the first direct move pays the peer dial
    # (cached afterwards), so the p50 must sit in steady state, not on
    # the handshake.
    mig_trace = [(rng.randint(1, 256, size=mig_prompt).tolist(), 2)
                 for _ in range(16)]
    mig_serve_cfg = ServeConfig(
        max_batch=max_batch, max_queue=len(mig_trace),
        block_size=block_size,
        max_prompt=mig_prompt, max_new_tokens=2,
        n_blocks=(max_batch + len(mig_trace)) * (mig_prompt
                                                 // block_size + 1))

    # One prefill -> one decode replica: the cleanest per-move
    # topology (the single peer dial amortizes over every move, and no
    # third replica's decode work interleaves into the timing).
    def migration_pass(mode):
        fleet = _thread_fleet(2)
        try:
            return _run_router_pass(
                mig_cfg, None, mig_trace, placement="affinity",
                n_replicas=2, n_prefill=1,
                serve_cfg=mig_serve_cfg, seed=seed, workers=fleet,
                handoff_compression="bf16", direct_migration=mode)
        finally:
            for h in fleet:
                h.close()

    if warmup:
        migration_pass("auto")   # jit the long-context buckets once
    mig = {"direct": [], "relayed": []}
    for _ in range(max(repeats, 1)):
        mig["direct"].append(migration_pass("auto"))
        mig["relayed"].append(migration_pass("off"))

    # Parity arms (structural, untimed): a single replica on the same
    # trace, and a split prefill/decode fleet exercising the handoff.
    ref_engine = ServeEngine(model_cfg, params, serve_cfg)
    rids = [ref_engine.submit(p, n) for p, n in trace]
    ref_engine.run_until_idle()
    ref = [ref_engine.result(r).tokens for r in rids]
    split = _run_router_pass(
        model_cfg, params, trace, placement="affinity",
        n_replicas=n_replicas, n_prefill=max(n_replicas // 2, 1),
        serve_cfg=serve_cfg, seed=seed)

    best = {a: _best_pass(ps) for a, ps in passes.items()}
    agg = {}
    for arm, ps in passes.items():
        hit = sum(s["hit_tokens"] for s in ps)
        looked = hit + sum(s["prefill_tokens"] for s in ps)
        pooled = [x for s in ps for x in s["first_token_s"]]
        v = percentile(pooled, 99)
        agg[arm] = {
            "hit_rate": round(hit / looked, 4) if looked else 0.0,
            "p99_first_ms": None if v is None else round(v * 1e3, 3),
        }
    ratio = (best["routed"]["tokens_per_sec_wall"]
             / best["random"]["tokens_per_sec_wall"]
             if best["random"]["tokens_per_sec_wall"] else None)
    identical = all(s["_tokens"] == ref
                    for ps in passes.values() for s in ps)
    rpc_keys = {}
    if cross_process:
        # The RPC tax: best cross-process pass over best in-process
        # pass, same trace, interleaved rounds. The bf16 split pass is
        # LOSSY (excluded from the parity key by design — its own
        # determinism is pinned in tests/test_rpc.py); it reports the
        # migration bytes the codec saves.
        tax = (best["rpc"]["tokens_per_sec_wall"]
               / best["routed"]["tokens_per_sec_wall"]
               if best["routed"]["tokens_per_sec_wall"] else None)
        raw = rpc_split["handoff_raw_bytes"]
        rpc_keys = {
            "serve_router_rpc_tokens_per_sec_per_chip":
                round(best["rpc"]["tokens_per_sec_wall"] / n_dev, 2),
            "serve_router_rpc_over_inproc":
                None if tax is None else round(tax, 3),
            "serve_router_rpc_p99_first_token_ms":
                agg["rpc"]["p99_first_ms"],
            "serve_router_rpc_tokens_identical":
                all(s["_tokens"] == ref for s in passes["rpc"]),
            "serve_router_rpc_handoff_count": rpc_split["handoffs"],
            "serve_router_rpc_handoff_bytes_saved_pct":
                (round(100.0 * (raw - rpc_split["handoff_wire_bytes"])
                       / raw, 2) if raw else None),
        }
    def _best_p50(ps):
        vals = [s["p50_migration_ms"] for s in ps
                if s["p50_migration_ms"] is not None]
        return min(vals) if vals else None

    d_p50, r_p50 = _best_p50(mig["direct"]), _best_p50(mig["relayed"])
    r_bytes = mig["relayed"][0]["migration_bytes"]
    d_bytes = mig["direct"][0]["migration_bytes"]
    mig_keys = {
        "serve_migration_p50_ms":
            None if d_p50 is None else round(d_p50, 3),
        "serve_migration_direct_over_relayed":
            (round(r_p50 / d_p50, 3)
             if d_p50 and r_p50 is not None else None),
        "serve_migration_bytes_saved_pct":
            (round(100.0 * (r_bytes - d_bytes) / r_bytes, 2)
             if r_bytes else None),
        "serve_migration_direct_count":
            sum(s["direct_migrations"] for s in mig["direct"]),
        # bf16 is idempotent, so ONE codec pass (direct) must emit
        # bitwise the streams of TWO (relayed) — pinned here and in
        # tests/test_rpc.py.
        "serve_migration_tokens_identical":
            all(s["_tokens"] == mig["relayed"][0]["_tokens"]
                for ps in mig.values() for s in ps),
    }
    return {
        **rpc_keys,
        **mig_keys,
        "serve_router_tokens_per_sec_per_chip":
            round(best["routed"]["tokens_per_sec_wall"] / n_dev, 2),
        "serve_router_random_tokens_per_sec_per_chip":
            round(best["random"]["tokens_per_sec_wall"] / n_dev, 2),
        "serve_router_over_random":
            None if ratio is None else round(ratio, 3),
        "serve_router_prefix_hit_rate": agg["routed"]["hit_rate"],
        "serve_router_random_prefix_hit_rate": agg["random"]["hit_rate"],
        "serve_router_p99_first_token_ms": agg["routed"]["p99_first_ms"],
        "serve_router_random_p99_first_token_ms":
            agg["random"]["p99_first_ms"],
        "serve_router_handoff_count": split["handoffs"],
        "serve_router_replica_count": n_replicas,
        "serve_router_tokens_identical":
            identical and split["_tokens"] == ref,
    }


def run_trace_overhead_benchmark(n_requests: int = 32, *, seed: int = 0,
                                 model_cfg=None, max_batch: int = 8,
                                 block_size: int = 8, warmup: bool = True,
                                 repeats: int = 4) -> dict:
    """Observability-tax benchmark (ISSUE 20), two keys:

    * ``serve_trace_overhead_pct`` — throughput tax of per-request
      trace tagging (every submit minted, every engine span carrying
      ids) vs the identical workload untagged. Both arms run on ONE
      engine, so compiled functions, allocator layout, and caches are
      shared and the only per-pass difference is the tagging. The arm
      order flips every round (plain-first, then traced-first, ...) and
      the medians compare, so a monotonic warm-up drift — which dwarfs
      the tagging cost on small runs — cancels instead of crediting
      whichever arm ran later. Target <2% (the always-on promise);
      UNGATED — a sub-percent number's round-over-round swing is
      scheduler noise, not a regression signal.
    * ``flight_dump_ms`` — wall time of one full-ring (4096-slot)
      flight-recorder dump, best of 5: the postmortem's cost when a
      fatal-signal handler calls it. UNGATED for the same reason.
    """
    import os
    import tempfile

    import jax
    import jax.numpy as jnp

    from horovod_tpu.common import basics
    from horovod_tpu.metrics import (
        flight_clear, flight_dump, flight_record,
    )
    from horovod_tpu.models import TransformerConfig, init_transformer
    from horovod_tpu.serve.engine import ServeConfig, ServeEngine
    from horovod_tpu.serve.trace import mint_trace_id

    if model_cfg is None:
        model_cfg = TransformerConfig.tiny(dtype=jnp.float32, remat=False)
    params = init_transformer(model_cfg, jax.random.PRNGKey(0))
    trace = make_trace(n_requests, seed=seed)
    max_prompt = max(len(p) for p, _ in trace)
    max_new = max(n for _, n in trace)
    cfg = ServeConfig(max_batch=max_batch, max_queue=max(len(trace), 8),
                      block_size=block_size, max_prompt=max_prompt,
                      max_new_tokens=max_new, prefix_caching=False)
    engine = ServeEngine(model_cfg, params, cfg)

    def _pass(label):
        t0 = time.perf_counter()
        engine.metrics.reset()
        rids = []
        for i, (p, n) in enumerate(trace):
            tid = (mint_trace_id(i, salt=seed, sample=1.0)
                   if label == "traced" else 0)
            rids.append(engine.submit(p, n, trace_id=tid))
        engine.run_until_idle()
        dt = time.perf_counter() - t0
        total = sum(len(engine.result(r).tokens) for r in rids)
        return total / dt

    if warmup:
        for label in ("plain", "traced"):
            _pass(label)
    tps = {"traced": [], "plain": []}
    for r in range(max(repeats, 1)):
        order = ("plain", "traced") if r % 2 == 0 else ("traced", "plain")
        for label in order:
            tps[label].append(_pass(label))
    med_on = statistics.median(tps["traced"])
    med_off = statistics.median(tps["plain"])
    overhead = (med_off / med_on - 1.0) * 100.0 if med_on else None

    # Full-ring dump cost: pad the ring to capacity, then time the
    # same text render the fatal-signal handler runs.
    flight_clear()
    for i in range(4096):
        flight_record(basics.FLIGHT_REQUEUE, i, 0)
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "flight-bench.txt")
        dump_s = []
        for _ in range(5):
            t0 = time.perf_counter()
            ok = flight_dump(path)
            dump_s.append(time.perf_counter() - t0)
            assert ok
    flight_clear()
    return {
        "serve_trace_overhead_pct":
            None if overhead is None else round(overhead, 2),
        "flight_dump_ms": round(min(dump_s) * 1e3, 3),
    }


def main() -> None:
    out = run_serving_benchmark()
    out.update(run_prefix_benchmark())
    out.update(run_spec_benchmark())
    out.update(run_router_benchmark())
    out.update(run_trace_overhead_benchmark())
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
