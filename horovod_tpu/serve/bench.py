"""Serving benchmark: continuous vs static batching on a mixed trace.

The serving claim worth measuring (Orca/vLLM, and the MLPerf-pod
motivation of reporting tails next to throughput): on traffic with
mixed prompt/output lengths, iteration-level admission keeps the
decode batch full while a static scheduler idles slots waiting for
the batch's straggler. Both schedulers here run the SAME jitted
prefill/decode programs and the same KV pool — the only variable is
admission policy (``ServeConfig.scheduling``), so the ratio isolates
the scheduling win.

Run directly (CPU-friendly):
    JAX_PLATFORMS=cpu python -m horovod_tpu.serve.bench
or let the repo-level ``bench.py`` fold the metrics into its round
payload (``serve_tokens_per_sec_per_chip``,
``serve_p99_first_token_ms``, ...).
"""

from __future__ import annotations

import json
import time
from typing import List, Tuple

import numpy as np


def make_trace(n_requests: int = 40, *, seed: int = 0,
               min_prompt: int = 4, max_prompt: int = 32,
               min_new: int = 8, max_new: int = 64,
               vocab: int = 256) -> List[Tuple[List[int], int]]:
    """Deterministic mixed-length request trace:
    ``[(prompt_tokens, max_new_tokens), ...]``."""
    rng = np.random.RandomState(seed)
    # Callers shrink max_* freely (e.g. a tiny-model demo); the lower
    # bounds follow rather than erroring on an empty range.
    min_prompt = min(min_prompt, max_prompt)
    min_new = min(min_new, max_new)
    trace = []
    for _ in range(n_requests):
        plen = int(rng.randint(min_prompt, max_prompt + 1))
        nnew = int(rng.randint(min_new, max_new + 1))
        prompt = rng.randint(1, vocab, size=plen).astype(np.int32).tolist()
        trace.append((prompt, nnew))
    return trace


def _run_trace(engine, trace) -> dict:
    """Submit the whole trace up front (closed-loop burst — worst case
    for admission) and serve to completion; returns the engine metrics
    snapshot plus wall-clock throughput."""
    t0 = time.perf_counter()
    engine.metrics.reset()
    rids = [engine.submit(p, n) for p, n in trace]
    engine.run_until_idle()
    dt = time.perf_counter() - t0
    total = sum(len(engine.result(r).tokens) for r in rids)
    snap = engine.metrics.snapshot()
    snap["wall_s"] = round(dt, 3)
    snap["tokens_total"] = total
    snap["tokens_per_sec_wall"] = round(total / dt, 2)
    return snap


def run_serving_benchmark(n_requests: int = 40, *, seed: int = 0,
                          model_cfg=None, max_batch: int = 8,
                          block_size: int = 8, warmup: bool = True,
                          repeats: int = 2) -> dict:
    """Measure continuous vs static batching throughput and latency
    tails on the same mixed-length trace. Returns the flat metric dict
    the repo benchmark folds into its payload.

    Each scheduler is measured ``repeats`` times and the best pass
    wins (the busbw protocol's rationale: on a timeshared host a
    single pass can eat scheduler interference that has nothing to do
    with the engine; the least-interfered pass is the comparable one).
    """
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models import TransformerConfig, init_transformer
    from horovod_tpu.serve.engine import ServeConfig, ServeEngine

    if model_cfg is None:
        # f32 tiny shape: CPU-fast, and the benchmark isolates
        # scheduling, not matmul throughput.
        model_cfg = TransformerConfig.tiny(dtype=jnp.float32, remat=False)
    params = init_transformer(model_cfg, jax.random.PRNGKey(0))
    trace = make_trace(n_requests, seed=seed)
    max_prompt = max(len(p) for p, _ in trace)
    max_new = max(n for _, n in trace)
    n_dev = jax.device_count()

    snaps = {}
    for scheduling in ("continuous", "static"):
        cfg = ServeConfig(
            max_batch=max_batch, max_queue=max(len(trace), 8),
            block_size=block_size, max_prompt=max_prompt,
            max_new_tokens=max_new, scheduling=scheduling)
        engine = ServeEngine(model_cfg, params, cfg)
        if warmup:
            # Same trace once untimed: compiles every (batch, prompt)
            # bucket this trace touches, so the measured pass times
            # steady-state serving, not XLA.
            _run_trace(engine, trace)
        best = None
        for _ in range(max(repeats, 1)):
            snap = _run_trace(engine, trace)
            if (best is None
                    or snap["tokens_per_sec_wall"]
                    > best["tokens_per_sec_wall"]):
                best = snap
        snaps[scheduling] = best

    cont, stat = snaps["continuous"], snaps["static"]
    ratio = (cont["tokens_per_sec_wall"] / stat["tokens_per_sec_wall"]
             if stat["tokens_per_sec_wall"] else None)
    return {
        "serve_tokens_per_sec_per_chip":
            round(cont["tokens_per_sec_wall"] / n_dev, 2),
        "serve_static_tokens_per_sec_per_chip":
            round(stat["tokens_per_sec_wall"] / n_dev, 2),
        "serve_continuous_over_static":
            None if ratio is None else round(ratio, 3),
        "serve_p50_first_token_ms": cont["p50_first_token_ms"],
        "serve_p99_first_token_ms": cont["p99_first_token_ms"],
        "serve_p50_per_token_ms": cont["p50_per_token_ms"],
        "serve_p99_per_token_ms": cont["p99_per_token_ms"],
        "serve_batch_occupancy": cont["batch_occupancy"],
        "serve_static_batch_occupancy": stat["batch_occupancy"],
        "serve_decode_steps": cont["decode_steps"],
        "serve_static_decode_steps": stat["decode_steps"],
    }


def main() -> None:
    print(json.dumps(run_serving_benchmark(), indent=2))


if __name__ == "__main__":
    main()
