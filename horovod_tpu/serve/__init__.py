"""Continuous-batching inference serving for the sharded transformer.

The inference workload layer the training-only reference never had:
an iteration-level scheduler (:class:`ServeEngine`) drives jitted
prefill/decode step functions (:mod:`horovod_tpu.serve.decode`) over a
paged KV cache (:mod:`horovod_tpu.serve.kv_cache`) on the same
``jax.sharding.Mesh`` the trainers use, and reports throughput + tail
latency through :mod:`horovod_tpu.serve.metrics`. Above the single
engine, :mod:`horovod_tpu.serve.router` runs a fleet: N replicas
behind a cache-affinity admission router with prefill/decode pools
(KV handoff) and deadline-class load shedding. The fleet spans
processes: :mod:`horovod_tpu.serve.rpc` lifts the engine seam onto a
length-prefixed RPC framing over the native vectored TCP transport,
:mod:`horovod_tpu.serve.worker` runs one engine per worker process,
and the router drives local and remote replicas identically
(heartbeat liveness, dead-worker requeue, drains that migrate RUNNING
decodes).

Quick start::

    from horovod_tpu.models import TransformerConfig, init_transformer
    from horovod_tpu import serve

    cfg = TransformerConfig.tiny()
    params = init_transformer(cfg, jax.random.PRNGKey(0))
    engine = serve.ServeEngine(cfg, params, serve.ServeConfig(max_batch=8))
    rid = engine.submit(prompt_tokens, max_new_tokens=32)
    while engine.pending:
        engine.step()
    print(engine.result(rid).tokens)

See ``docs/serving.md`` for architecture and tuning.
"""

from horovod_tpu.serve.engine import (  # noqa: F401
    PrefillHandoff,
    QueueFull,
    RequestResult,
    ServeConfig,
    ServeEngine,
)
from horovod_tpu.serve.kv_cache import (  # noqa: F401
    BlockAllocator,
    KVCache,
    NULL_BLOCK,
    OutOfBlocks,
    block_hash,
    hash_chain,
    init_kv_cache,
    pick_bucket,
)
from horovod_tpu.serve.decode import make_serve_fns  # noqa: F401
from horovod_tpu.serve.metrics import ServeMetrics, percentile  # noqa: F401
from horovod_tpu.serve.speculative import (  # noqa: F401
    DraftConfig,
    SpecDecoder,
    accept_greedy,
    make_draft_target_params,
)
from horovod_tpu.serve.router import (  # noqa: F401
    FleetMetrics,
    FleetSaturated,
    RouterConfig,
    ServeRouter,
)
from horovod_tpu.serve.rpc import (  # noqa: F401
    RPC_PROTOCOL_VERSION,
    RemoteReplica,
    RpcConn,
    RpcConnectionError,
    RpcError,
    RpcProtocolError,
    WorkerHandle,
    connect_worker,
    spawn_worker,
)
from horovod_tpu.serve.bench import (  # noqa: F401
    make_multi_tenant_trace,
    make_shared_prefix_trace,
    make_trace,
    run_prefix_benchmark,
    run_router_benchmark,
    run_serving_benchmark,
    run_spec_benchmark,
)
