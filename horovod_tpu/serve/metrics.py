"""Serving metrics: throughput, latency tails, and scheduler health.

The MLPerf TPU-pod scaling writeup (PAPERS.md) motivates reporting
throughput *and* tail latency as first-class serving metrics — a
batch-packing change that raises tokens/sec while blowing p99
first-token latency is a regression for interactive traffic, and
neither number alone shows it.

Surfaces:

* :meth:`ServeMetrics.snapshot` — counters + percentiles as a flat
  dict (what ``bench.py`` folds into the round payload).
* :meth:`ServeMetrics.export_chrome_trace` — per-step spans in the
  chrome-tracing JSON format, viewable in the same ``chrome://tracing``
  / Perfetto UI as the host timeline (``hvd.start_timeline`` /
  ``horovodrun --timeline-filename``). Engine steps additionally run
  under ``jax.profiler.TraceAnnotation`` (see ``engine.py``) so device
  traces show ``serve:prefill`` / ``serve:decode`` phases with the
  same names — the convention :mod:`horovod_tpu.ops.xla_exec` uses for
  collectives.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from typing import Dict, List, Optional

#: Keep at most this many latency samples per series (drop-oldest);
#: long-running engines must not grow without bound.
MAX_SAMPLES = 100_000


def percentile(samples: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]); None on no samples."""
    if not samples:
        return None
    xs = sorted(samples)
    idx = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[idx]


#: Process-wide monotonic default for the per-engine ``instance``
#: label: N replicas sharing one exposition endpoint must not collide
#: on the bare ``serve_`` series names (Prometheus reads duplicate
#: unlabeled samples as one broken family, and fleet sums silently
#: undercount). An explicit instance (the router passes its replica
#: id) overrides the counter.
_instance_ids = itertools.count()


class ServeMetrics:
    def __init__(self, clock=time.perf_counter,
                 instance: Optional[str] = None):
        self._clock = clock
        self._allocator = None
        self._alloc_base = (0, 0, 0)
        self.instance = (str(next(_instance_ids)) if instance is None
                         else str(instance))
        self.reset()
        # Export through the process-wide telemetry endpoint: a scrape
        # of hvd.metrics_prometheus() (or the rank-0 metrics server)
        # covers training AND serving in one text format. Weakly bound
        # so an abandoned engine's metrics vanish with it.
        from horovod_tpu.metrics import register_exporter_weak
        register_exporter_weak(f"serve_{id(self)}", self, "prometheus")

    def reset(self) -> None:
        self.started_at = self._clock()
        self.tokens_generated = 0
        self.requests_submitted = 0
        self.requests_finished = 0
        self.requests_expired = 0
        self.requests_rejected = 0
        self.handoffs_in = 0
        self.handoffs_out = 0
        self.prefill_steps = 0
        self.decode_steps = 0
        self.queue_depth = 0
        self.max_queue_depth = 0
        self._occupancy_sum = 0.0
        # Token-granularity prefix-cache accounting: per admission,
        # how many prompt tokens were served out of the cache vs
        # prefilled. The block-granularity counters (hits / misses /
        # evictions) live on the attached BlockAllocator.
        self.prefix_hit_tokens = 0
        self.prefix_prefill_tokens = 0
        # Speculative decoding (serve/speculative.py): proposal /
        # acceptance tallies (their ratio is the token-weighted accept
        # rate) and the per-round draft / verify wall-time series.
        self.spec_rounds = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_draft_s: List[float] = []
        self.spec_verify_s: List[float] = []
        self.first_token_s: List[float] = []
        self.per_token_s: List[float] = []
        self._events: List[dict] = []
        # Allocator counters are lifetime totals; baseline them here
        # so snapshots report the same window as every other counter
        # in this object (reset-to-now), not engine-lifetime numbers.
        if self._allocator is not None:
            a = self._allocator
            self._alloc_base = (a.prefix_hits, a.prefix_misses,
                                a.evictions)

    def attach_allocator(self, allocator) -> None:
        """Let snapshots/trace export read the block pool's gauges
        (blocks in use, cached, high water) and prefix counters
        without the engine copying them in per step."""
        self._allocator = allocator
        self._alloc_base = (allocator.prefix_hits,
                            allocator.prefix_misses,
                            allocator.evictions)

    # -- recording ---------------------------------------------------

    def _span(self, name: str, t0: float, dur: float, **args) -> None:
        # chrome-tracing "complete" event; ts/dur in microseconds.
        # Same cap as the latency series: a long-running engine must
        # not grow host memory step by step.
        if len(self._events) >= MAX_SAMPLES:
            return
        ts = round((t0 - self.started_at) * 1e6, 1)
        self._events.append({
            "name": name, "ph": "X", "pid": 0, "tid": 0,
            "ts": ts, "dur": round(dur * 1e6, 1), "args": args})
        if (self._allocator is not None
                and len(self._events) < MAX_SAMPLES):
            # Pool occupancy as a counter track next to the spans:
            # live blocks vs warm (refcount-0 cached) blocks per step.
            self._events.append({
                "name": "kv_blocks", "ph": "C", "pid": 0, "tid": 0,
                "ts": ts, "args": {"in_use": self._allocator.n_used,
                                   "cached": self._allocator.n_cached}})

    def record_queue_depth(self, depth: int) -> None:
        self.queue_depth = depth
        self.max_queue_depth = max(self.max_queue_depth, depth)

    def _pool_gauges(self) -> dict:
        a = self._allocator
        if a is None:
            return {}
        return {"blocks_in_use": a.n_used, "blocks_cached": a.n_cached}

    def record_prefill(self, t0: float, dur_s: float, n_tokens: int,
                       offset: int = 0, trace: int = 0) -> None:
        """One prefill chunk of ``n_tokens`` starting at token
        ``offset`` (0 + whole prompt = the monolithic case);
        ``trace`` is the request's distributed trace id (0 =
        unsampled, omitted from the span)."""
        self.prefill_steps += 1
        extra = {"trace": trace} if trace else {}
        self._span("serve:prefill", t0, dur_s, n_tokens=n_tokens,
                   offset=offset, **extra, **self._pool_gauges())

    def record_prefix_lookup(self, hit_tokens: int,
                             suffix_tokens: int) -> None:
        """One admission's cache outcome: ``hit_tokens`` prompt tokens
        mapped from the prefix cache, ``suffix_tokens`` left to
        prefill. Their running ratio is the hit rate."""
        self.prefix_hit_tokens += hit_tokens
        self.prefix_prefill_tokens += suffix_tokens

    def record_prefix_extend(self, tokens: int) -> None:
        """Tokens converted from pending-prefill to cache hits by the
        engine's second cache walk at prefill time (same-step burst
        siblings publish between admission and prefill)."""
        self.prefix_hit_tokens += tokens
        self.prefix_prefill_tokens -= tokens

    def record_decode(self, t0: float, dur_s: float, n_active: int,
                      max_batch: int, traces=None) -> None:
        self.decode_steps += 1
        self.tokens_generated += n_active
        self._occupancy_sum += n_active / max_batch
        if len(self.per_token_s) < MAX_SAMPLES:
            # Every active sequence advanced one token this step, so
            # the step wall time IS the per-token latency sample.
            self.per_token_s.append(dur_s)
        # A decode step serves the whole batch, so it carries the
        # trace ids of every sampled sequence in it (plural key).
        extra = {"traces": list(traces)} if traces else {}
        self._span("serve:decode", t0, dur_s, n_active=n_active,
                   **extra, **self._pool_gauges())

    def record_spec_round(self, t0: float, draft_dur_s: float,
                          verify_dur_s: float, n_active: int,
                          max_batch: int, *, proposed: int,
                          accepted: int, emitted: int,
                          traces=None) -> None:
        """One speculative iteration: the k batched draft decode steps
        (one span) plus the single chunked verify step, with the
        round's proposal/acceptance tallies. Feeds the same
        throughput/occupancy series a plain decode step feeds so
        tokens/sec and batch_occupancy compare across speculative and
        plain engines; the per-token latency sample is the round wall
        time over tokens-per-sequence (a round delivers several tokens
        at once — the inter-token interval a client sees is the round
        amortized over them)."""
        self.spec_rounds += 1
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        self.tokens_generated += emitted
        self._occupancy_sum += n_active / max_batch
        dur = draft_dur_s + verify_dur_s
        if emitted and len(self.per_token_s) < MAX_SAMPLES:
            self.per_token_s.append(dur * n_active / emitted)
        if len(self.spec_draft_s) < MAX_SAMPLES:
            self.spec_draft_s.append(draft_dur_s)
        if len(self.spec_verify_s) < MAX_SAMPLES:
            self.spec_verify_s.append(verify_dur_s)
        extra = {"traces": list(traces)} if traces else {}
        self._span("serve:spec_draft", t0, draft_dur_s,
                   n_active=n_active, proposed=proposed, **extra)
        self._span("serve:spec_verify", t0 + draft_dur_s, verify_dur_s,
                   accepted=accepted, emitted=emitted, **extra,
                   **self._pool_gauges())

    def record_first_token(self, latency_s: float) -> None:
        # The first token comes out of prefill, not a decode step —
        # count it here so tokens/sec covers all generated tokens.
        self.tokens_generated += 1
        if len(self.first_token_s) < MAX_SAMPLES:
            self.first_token_s.append(latency_s)

    def record_submitted(self) -> None:
        self.requests_submitted += 1

    def record_withdrawn(self) -> None:
        """A queued request reclaimed by ``ServeEngine.withdraw``: it
        leaves without a result and will be re-counted wherever the
        router re-submits it, so it must not stay in this replica's
        submitted tally (fleet sums would report phantom in-flight
        requests forever)."""
        self.requests_submitted -= 1

    def record_finished(self) -> None:
        self.requests_finished += 1

    def record_expired(self) -> None:
        self.requests_expired += 1

    def record_rejected(self) -> None:
        self.requests_rejected += 1

    def record_handoff_out(self) -> None:
        """A completed prefill left this replica for a decode pool."""
        self.handoffs_out += 1

    def record_handoff_in(self) -> None:
        """A prefilled sequence arrived to decode on this replica."""
        self.handoffs_in += 1

    # -- export ------------------------------------------------------

    def snapshot(self) -> Dict[str, float]:
        elapsed = max(self._clock() - self.started_at, 1e-9)

        def ms(x):
            return None if x is None else round(x * 1e3, 3)

        # A speculative round occupies batch slots exactly like a
        # decode step — both feed the occupancy numerator, so both
        # count in the denominator.
        occ_steps = self.decode_steps + self.spec_rounds
        occ = self._occupancy_sum / occ_steps if occ_steps else 0.0
        looked_up = self.prefix_hit_tokens + self.prefix_prefill_tokens
        out = {
            "elapsed_s": round(elapsed, 3),
            "tokens_generated": self.tokens_generated,
            "tokens_per_sec": round(self.tokens_generated / elapsed, 2),
            "requests_submitted": self.requests_submitted,
            "requests_finished": self.requests_finished,
            "requests_expired": self.requests_expired,
            "requests_rejected": self.requests_rejected,
            "handoffs_in": self.handoffs_in,
            "handoffs_out": self.handoffs_out,
            "prefill_steps": self.prefill_steps,
            "decode_steps": self.decode_steps,
            "queue_depth": self.queue_depth,
            "max_queue_depth": self.max_queue_depth,
            "batch_occupancy": round(occ, 4),
            "prefix_cache_hit_rate": (
                round(self.prefix_hit_tokens / looked_up, 4)
                if looked_up else 0.0),
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefix_prefill_tokens": self.prefix_prefill_tokens,
            "p50_first_token_ms": ms(percentile(self.first_token_s, 50)),
            "p99_first_token_ms": ms(percentile(self.first_token_s, 99)),
            "p50_per_token_ms": ms(percentile(self.per_token_s, 50)),
            "p99_per_token_ms": ms(percentile(self.per_token_s, 99)),
            # Speculative decoding: counters are zeros on a plain
            # engine (so mixed-fleet rollups sum without key checks);
            # the accept rate is token-weighted (accepted DRAFT tokens
            # over proposed — correction tokens are the target's own
            # and count in neither).
            "spec_rounds": self.spec_rounds,
            "spec_proposed_total": self.spec_proposed,
            "spec_accepted_total": self.spec_accepted,
            "spec_accept_rate": (
                round(self.spec_accepted / self.spec_proposed, 4)
                if self.spec_proposed else 0.0),
            "p50_spec_draft_ms": ms(percentile(self.spec_draft_s, 50)),
            "p99_spec_draft_ms": ms(percentile(self.spec_draft_s, 99)),
            "p50_spec_verify_ms": ms(percentile(self.spec_verify_s, 50)),
            "p99_spec_verify_ms": ms(percentile(self.spec_verify_s, 99)),
        }
        if self._allocator is not None:
            a = self._allocator
            hits0, misses0, evict0 = self._alloc_base
            out.update({
                # Block-pool health: peak-vs-current reservation cost
                # and how much "free" capacity is really warm cache.
                # Counters are deltas since reset() (same window as
                # the token counters above); the high-water gauge is
                # engine-lifetime by design (capacity planning).
                "kv_blocks_in_use": a.n_used,
                "kv_blocks_cached": a.n_cached,
                "kv_blocks_high_water": a.high_water,
                "prefix_block_hits": a.prefix_hits - hits0,
                "prefix_block_misses": a.prefix_misses - misses0,
                "prefix_block_evictions": a.evictions - evict0,
            })
        return out

    def prometheus(self) -> str:
        """This snapshot as Prometheus text, rendered through the SAME
        exposition helper as the native registry
        (``horovod_tpu.metrics.render_gauges``) under the ``serve_``
        prefix — serving and training export one format, one endpoint
        (docs/observability.md). Every sample carries this engine's
        ``instance`` label so N replicas in one process stay
        distinguishable in one scrape and fleet-level PromQL sums
        (``sum(serve_tokens_generated)``) are correct."""
        from horovod_tpu.metrics import render_gauges
        return render_gauges("serve", self.snapshot(),
                             labels={"instance": self.instance})

    def trace_metadata(self, **extra) -> dict:
        """Timebase anchor for :meth:`export_chrome_trace` and the
        RPC ``export_trace`` verb: span ``ts`` values are microseconds
        since ``started_at`` on this engine's clock, and the
        ``(clock_now, wall_now)`` pair taken here lets
        ``bin/hvd-trace merge`` map them onto any other process's
        clock (docs/observability.md "One timebase")."""
        md = {
            "kind": "engine",
            "instance": self.instance,
            "pid": os.getpid(),
            "started_at": self.started_at,
            "clock_now": self._clock(),
            "wall_now": time.time(),
        }
        md.update(extra)
        return md

    def export_chrome_trace(self, path: str, **extra) -> None:
        """Write recorded step spans as a chrome-tracing file (the
        timeline format the rest of the framework emits), with the
        :meth:`trace_metadata` anchor so merged fleet views can
        re-anchor the spans onto one timebase."""
        with open(path, "w") as f:
            json.dump({"traceEvents": self._events,
                       "displayTimeUnit": "ms",
                       "metadata": self.trace_metadata(**extra)}, f)
