"""Serving metrics: throughput, latency tails, and scheduler health.

The MLPerf TPU-pod scaling writeup (PAPERS.md) motivates reporting
throughput *and* tail latency as first-class serving metrics — a
batch-packing change that raises tokens/sec while blowing p99
first-token latency is a regression for interactive traffic, and
neither number alone shows it.

Surfaces:

* :meth:`ServeMetrics.snapshot` — counters + percentiles as a flat
  dict (what ``bench.py`` folds into the round payload).
* :meth:`ServeMetrics.export_chrome_trace` — per-step spans in the
  chrome-tracing JSON format, viewable in the same ``chrome://tracing``
  / Perfetto UI as the host timeline (``hvd.start_timeline`` /
  ``horovodrun --timeline-filename``). Engine steps additionally run
  under ``jax.profiler.TraceAnnotation`` (see ``engine.py``) so device
  traces show ``serve:prefill`` / ``serve:decode`` phases with the
  same names — the convention :mod:`horovod_tpu.ops.xla_exec` uses for
  collectives.
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

#: Keep at most this many latency samples per series (drop-oldest);
#: long-running engines must not grow without bound.
MAX_SAMPLES = 100_000


def percentile(samples: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]); None on no samples."""
    if not samples:
        return None
    xs = sorted(samples)
    idx = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[idx]


class ServeMetrics:
    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self.reset()

    def reset(self) -> None:
        self.started_at = self._clock()
        self.tokens_generated = 0
        self.requests_submitted = 0
        self.requests_finished = 0
        self.requests_expired = 0
        self.requests_rejected = 0
        self.prefill_steps = 0
        self.decode_steps = 0
        self.queue_depth = 0
        self.max_queue_depth = 0
        self._occupancy_sum = 0.0
        self.first_token_s: List[float] = []
        self.per_token_s: List[float] = []
        self._events: List[dict] = []

    # -- recording ---------------------------------------------------

    def _span(self, name: str, t0: float, dur: float, **args) -> None:
        # chrome-tracing "complete" event; ts/dur in microseconds.
        # Same cap as the latency series: a long-running engine must
        # not grow host memory step by step.
        if len(self._events) >= MAX_SAMPLES:
            return
        self._events.append({
            "name": name, "ph": "X", "pid": 0, "tid": 0,
            "ts": round((t0 - self.started_at) * 1e6, 1),
            "dur": round(dur * 1e6, 1), "args": args})

    def record_queue_depth(self, depth: int) -> None:
        self.queue_depth = depth
        self.max_queue_depth = max(self.max_queue_depth, depth)

    def record_prefill(self, t0: float, dur_s: float, prompt_len: int) -> None:
        self.prefill_steps += 1
        self._span("serve:prefill", t0, dur_s, prompt_len=prompt_len)

    def record_decode(self, t0: float, dur_s: float, n_active: int,
                      max_batch: int) -> None:
        self.decode_steps += 1
        self.tokens_generated += n_active
        self._occupancy_sum += n_active / max_batch
        if len(self.per_token_s) < MAX_SAMPLES:
            # Every active sequence advanced one token this step, so
            # the step wall time IS the per-token latency sample.
            self.per_token_s.append(dur_s)
        self._span("serve:decode", t0, dur_s, n_active=n_active)

    def record_first_token(self, latency_s: float) -> None:
        # The first token comes out of prefill, not a decode step —
        # count it here so tokens/sec covers all generated tokens.
        self.tokens_generated += 1
        if len(self.first_token_s) < MAX_SAMPLES:
            self.first_token_s.append(latency_s)

    def record_submitted(self) -> None:
        self.requests_submitted += 1

    def record_finished(self) -> None:
        self.requests_finished += 1

    def record_expired(self) -> None:
        self.requests_expired += 1

    def record_rejected(self) -> None:
        self.requests_rejected += 1

    # -- export ------------------------------------------------------

    def snapshot(self) -> Dict[str, float]:
        elapsed = max(self._clock() - self.started_at, 1e-9)

        def ms(x):
            return None if x is None else round(x * 1e3, 3)

        occ = (self._occupancy_sum / self.decode_steps
               if self.decode_steps else 0.0)
        return {
            "elapsed_s": round(elapsed, 3),
            "tokens_generated": self.tokens_generated,
            "tokens_per_sec": round(self.tokens_generated / elapsed, 2),
            "requests_submitted": self.requests_submitted,
            "requests_finished": self.requests_finished,
            "requests_expired": self.requests_expired,
            "requests_rejected": self.requests_rejected,
            "prefill_steps": self.prefill_steps,
            "decode_steps": self.decode_steps,
            "queue_depth": self.queue_depth,
            "max_queue_depth": self.max_queue_depth,
            "batch_occupancy": round(occ, 4),
            "p50_first_token_ms": ms(percentile(self.first_token_s, 50)),
            "p99_first_token_ms": ms(percentile(self.first_token_s, 99)),
            "p50_per_token_ms": ms(percentile(self.per_token_s, 50)),
            "p99_per_token_ms": ms(percentile(self.per_token_s, 99)),
        }

    def export_chrome_trace(self, path: str) -> None:
        """Write recorded step spans as a chrome-tracing file (the
        timeline format the rest of the framework emits)."""
        with open(path, "w") as f:
            json.dump({"traceEvents": self._events,
                       "displayTimeUnit": "ms"}, f)
