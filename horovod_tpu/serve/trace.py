"""Request-scoped distributed tracing for the serving fleet.

The serve plane already records per-step chrome spans on every engine
(:meth:`ServeMetrics.record_prefill` / ``record_decode``), but a
cross-process fleet scatters one request's life across processes with
*different* ``perf_counter`` epochs and no shared request identity:
you can see that *a* prefill ran on worker 2, not that it was *your*
request's prefill. This module adds the missing identity and the
router-side half of the timeline:

* **Trace ids.** The router mints one 64-bit id per request at submit
  (:func:`mint_trace_id` — FNV-1a over (salt, rid), deterministic for
  a fixed fleet seed, never 0: id 0 means "unsampled" everywhere).
  The id rides the RPC frame header (``rpc.py`` protocol v2) to the
  worker, which tags the engine spans it already records; the router
  tags its own queue-wait / placement / handoff / e2e spans here.
* **Sampling.** ``HOROVOD_TRACE_SAMPLE`` (sane-env style: a fraction
  in [0, 1], default 1 = trace everything) decides per request,
  deterministically by rid hash — the same request traces or doesn't
  across reruns. An unsampled request carries trace id 0 and pays
  nothing beyond the sampling test itself; the <2% overhead guard in
  ``serve/bench.py`` (``serve_trace_overhead_pct``) pins the sampled
  cost.
* **One timebase.** Every export carries a ``(clock_now, wall_now)``
  anchor pair in its metadata; remote workers additionally get the
  router's RTT-estimated ``clock_offset`` (heartbeat midpoints, the
  PR 11 age-re-anchor discipline extended to a persistent offset).
  ``bin/hvd-trace merge`` maps every span onto the router's wall
  clock with them.

See docs/observability.md "Distributed request tracing".
"""

from __future__ import annotations

import json
import os
import time
import warnings
from typing import Any, Dict, List, Optional

#: Sane-env sampling knob: fraction of requests to trace, default 1.0
#: (everything). 0 disables minting entirely. Documented in
#: docs/observability.md.
TRACE_SAMPLE_ENV = "HOROVOD_TRACE_SAMPLE"

#: Span cap, same drop-newest policy as ``ServeMetrics`` events.
MAX_TRACE_EVENTS = 100_000

_warned_bad_sample = False


def trace_sample_rate() -> float:
    """:data:`TRACE_SAMPLE_ENV` as a fraction in [0, 1]. Lenient
    parse in the sane-env tradition: unset/empty = 1.0, garbage warns
    once and falls back to 1.0 (a typo must not silently kill the
    whole observability plane), and numeric values clamp into
    range."""
    global _warned_bad_sample
    raw = os.environ.get(TRACE_SAMPLE_ENV, "").strip()
    if not raw:
        return 1.0
    try:
        val = float(raw)
    except ValueError:
        if not _warned_bad_sample:
            _warned_bad_sample = True
            warnings.warn(
                f"{TRACE_SAMPLE_ENV}={raw!r} is not a number; tracing "
                "every request (the default)", stacklevel=2)
        return 1.0
    return min(max(val, 0.0), 1.0)


def _fnv1a64(*parts: int) -> int:
    h = 0xcbf29ce484222325
    for p in parts:
        v = p & 0xFFFFFFFFFFFFFFFF
        for _ in range(8):
            h ^= v & 0xFF
            h = (h * 0x100000001b3) & 0xFFFFFFFFFFFFFFFF
            v >>= 8
    return h


def mint_trace_id(rid: int, salt: int = 0,
                  sample: Optional[float] = None) -> int:
    """Trace id for router request ``rid``: FNV-1a over (salt, rid),
    never 0 (0 = unsampled, everywhere). The sampling decision is
    deterministic by rid hash, so a replayed seeded run traces the
    same requests; ``sample`` overrides the env knob (tests)."""
    rate = trace_sample_rate() if sample is None else sample
    if rate <= 0.0:
        return 0
    h = _fnv1a64(salt, rid)
    if rate < 1.0 and (h % 1_000_000) >= int(rate * 1_000_000):
        return 0
    return h or 1


class RouterTrace:
    """Chrome-event recorder for the router's half of a request's
    life: submit, queue wait, placement verdict, RPC wire time,
    handoffs/migrations, requeues, and the end-to-end span. All
    timestamps are on the ROUTER clock (``ts`` microseconds since
    construction, the same convention as ``ServeMetrics._span``);
    :meth:`export` writes the anchor pair that maps them onto wall
    time."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self.started_at = clock()
        self._events: List[dict] = []

    def _ts(self, t: float) -> float:
        return round((t - self.started_at) * 1e6, 1)

    def span(self, name: str, t0: float, dur_s: float,
             trace: int = 0, **args: Any) -> None:
        if len(self._events) >= MAX_TRACE_EVENTS:
            return
        if trace:
            args["trace"] = trace
        self._events.append({
            "name": name, "ph": "X", "pid": 0, "tid": 0,
            "ts": self._ts(t0), "dur": round(dur_s * 1e6, 1),
            "args": args})

    def instant(self, name: str, t: Optional[float] = None,
                trace: int = 0, **args: Any) -> None:
        if len(self._events) >= MAX_TRACE_EVENTS:
            return
        if trace:
            args["trace"] = trace
        self._events.append({
            "name": name, "ph": "i", "s": "t", "pid": 0, "tid": 0,
            "ts": self._ts(self._clock() if t is None else t),
            "args": args})

    @property
    def events(self) -> List[dict]:
        return self._events

    def metadata(self, **extra: Any) -> Dict[str, Any]:
        """Anchor metadata for :meth:`export`: the ``(clock_now,
        wall_now)`` pair every merge timebase computation needs, plus
        whatever the caller adds (kind/instance/offsets)."""
        md = {
            "kind": "router",
            "pid": os.getpid(),
            "started_at": self.started_at,
            "clock_now": self._clock(),
            "wall_now": time.time(),
            "clock_offset": 0.0,
        }
        md.update(extra)
        return md

    def export(self, path: str, **extra: Any) -> None:
        with open(path, "w") as f:
            json.dump({"traceEvents": self._events,
                       "displayTimeUnit": "ms",
                       "metadata": self.metadata(**extra)}, f)
