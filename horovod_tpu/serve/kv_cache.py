"""Paged KV-cache management for continuous-batching inference.

The decode batch packs variable-length sequences, so per-sequence
contiguous caches would either waste HBM on worst-case ``max_seq``
slots or force a recompile whenever the packing changes. Instead the
cache is a pool of fixed-size **blocks** (vLLM's PagedAttention
layout): device arrays shaped ``[L, n_blocks, block_size, Hkv, Dh]``
plus a host-side :class:`BlockAllocator` handing out block ids. Each
sequence owns a *block table* (row of physical block ids); the jitted
decode step gathers K/V pages through the table, so batch membership
can change every iteration without touching compiled code.

Block 0 is reserved as the **null block**: padded batch slots and
masked writes are routed there so the scatter in the decode step never
needs a branch, and its contents are never read (attention masks by
sequence length).

Reference analog: none — the reference framework (training-only
Horovod) has no inference path at all; this layout is the TPU-serving
standard (PagedAttention, vLLM SOSP'23).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

NULL_BLOCK = 0


class OutOfBlocks(RuntimeError):
    """Raised by :meth:`BlockAllocator.alloc` when the pool cannot
    serve the request — the engine's admission backpressure signal."""


class BlockAllocator:
    """Host-side free-list over the device block pool.

    Paged allocation has no external fragmentation: any free block can
    serve any sequence, so ``can_alloc(n)`` is simply ``n <= n_free``.
    The free list is LIFO so recently-retired blocks (likely still
    warm in cache/HBM pages) are reused first, and allocation order is
    deterministic for tests.
    """

    def __init__(self, n_blocks: int, block_size: int):
        if n_blocks < 2:
            raise ValueError(
                f"need at least 2 blocks (1 usable + null), got {n_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.n_blocks = n_blocks
        self.block_size = block_size
        # Block 0 is the null sink — never handed out.
        self._free: List[int] = list(range(n_blocks - 1, 0, -1))
        # Mirror of _free for O(1) double-free checks: retiring a long
        # sequence against a mostly-free pool was O(freed x n_free)
        # inside the engine's step loop with the list scan.
        self._free_set = set(self._free)
        self._used = 0
        self._high_water = 0

    @property
    def n_free(self) -> int:
        return len(self._free)

    @property
    def n_used(self) -> int:
        return self._used

    @property
    def high_water(self) -> int:
        """Peak concurrent blocks in use (capacity-planning stat)."""
        return self._high_water

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache entries."""
        return -(-max(n_tokens, 0) // self.block_size)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise OutOfBlocks(
                f"requested {n} KV blocks, {len(self._free)} free "
                f"(pool {self.n_blocks - 1} x {self.block_size} tokens)")
        out = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(out)
        self._used += n
        self._high_water = max(self._high_water, self._used)
        return out

    def free(self, blocks: List[int]) -> None:
        seen = set()
        for b in blocks:
            if not 0 < b < self.n_blocks:
                raise ValueError(f"freeing invalid block id {b}")
            if b in self._free_set or b in seen:
                raise ValueError(f"double free of block {b}")
            seen.add(b)
        # Validate-all-then-mutate: the pool is untouched on error.
        self._free.extend(blocks)
        self._free_set.update(blocks)
        self._used -= len(blocks)


@dataclasses.dataclass
class KVCache:
    """Device-side paged cache: one K and one V array per model,
    layer-stacked on the leading dim to match the transformer's
    scan-over-layers parameter layout."""

    k: Any  # [L, n_blocks, block_size, Hkv, Dh]
    v: Any  # [L, n_blocks, block_size, Hkv, Dh]
    block_size: int
    n_blocks: int

    @property
    def max_blocks_per_seq(self) -> int:
        # Shapes are static per engine: table width is the worst case.
        return self.n_blocks


def init_kv_cache(cfg, n_blocks: int, block_size: int,
                  mesh: Optional[Any] = None,
                  dtype: Optional[Any] = None) -> KVCache:
    """Allocate the zeroed block pool on device.

    With a mesh, KV heads are sharded over ``tp`` (matching the
    tp-sharded ``wk``/``wv`` projections so the decode step's cache
    writes stay local to each tp shard — no resharding on the hot
    loop, the EQuARX-motivated property of keeping collectives on ICI
    inside the jitted step).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    dtype = dtype or cfg.dtype
    shape = (cfg.n_layers, n_blocks, block_size, cfg.n_kv_heads,
             cfg.head_dim)
    sharding = None
    if mesh is not None:
        tp = mesh.shape.get("tp", 1)
        if tp > 1 and cfg.n_kv_heads % tp == 0:
            sharding = NamedSharding(mesh, P(None, None, None, "tp", None))
    def zeros():
        return jnp.zeros(shape, dtype)
    if sharding is not None:
        k = jax.jit(zeros, out_shardings=sharding)()
        v = jax.jit(zeros, out_shardings=sharding)()
    else:
        k, v = zeros(), zeros()
    return KVCache(k=k, v=v, block_size=block_size, n_blocks=n_blocks)


def pick_bucket(n: int, buckets: Tuple[int, ...]) -> int:
    """Smallest bucket >= n (buckets ascending). Bucketing pads batch
    and prompt shapes to a short menu of sizes so the jit cache stays
    small and hot — the no-per-request-recompilation invariant."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} exceeds largest bucket {buckets[-1]}")
