"""Paged KV-cache management for continuous-batching inference.

The decode batch packs variable-length sequences, so per-sequence
contiguous caches would either waste HBM on worst-case ``max_seq``
slots or force a recompile whenever the packing changes. Instead the
cache is a pool of fixed-size **blocks** (vLLM's PagedAttention
layout): device arrays shaped ``[L, n_blocks, block_size, Hkv, Dh]``
plus a host-side :class:`BlockAllocator` handing out block ids. Each
sequence owns a *block table* (row of physical block ids); the jitted
decode step gathers K/V pages through the table, so batch membership
can change every iteration without touching compiled code.

Block 0 is reserved as the **null block**: padded batch slots and
masked writes are routed there so the scatter in the decode step never
needs a branch, and its contents are never read (attention masks by
sequence length).

Blocks are **refcounted and content-addressed**: a full (immutable)
block can be published under a chained content hash
(``block_hash(parent_hash, block_tokens)``) and later requests whose
prompts share that whole-block prefix map the cached block straight
into their block table instead of recomputing its K/V (prefix
caching, the vLLM/SGLang "automatic prefix cache" design). A block
whose refcount drops to zero keeps its contents and parks in an LRU
pool; it is only *evicted* (contents forgotten) when a fresh
allocation finds the plain free list empty — so "free" capacity
usually means "still cached".

Reference analog: none — the reference framework (training-only
Horovod) has no inference path at all; this layout is the TPU-serving
standard (PagedAttention, vLLM SOSP'23).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

NULL_BLOCK = 0


def block_hash(parent: bytes, tokens) -> bytes:
    """Chained content hash of one full block: the parent is the hash
    of the preceding block (``b""`` for the first), so equal hashes
    imply an equal whole-token prefix, not just an equal block."""
    m = hashlib.blake2b(parent, digest_size=16)
    m.update(np.asarray(tokens, np.int64).tobytes())
    return m.digest()


def hash_chain(prompt, block_size: int) -> List[bytes]:
    """Chained content hash per full prompt block (the partial tail
    block, if any, stays private and unhashed). One chain entry per
    whole block; entry ``i`` summarizes the whole prefix through block
    ``i``. Shared between the engine (publish/lookup at admission) and
    the fleet router (cache-affinity placement walks replicas' indexes
    against the same chain) — both sides MUST hash identically or
    affinity routes to replicas whose index can never hit."""
    chain, h = [], b""
    for i in range(len(prompt) // block_size):
        h = block_hash(h, prompt[i * block_size:(i + 1) * block_size])
        chain.append(h)
    return chain


class OutOfBlocks(RuntimeError):
    """Raised by :meth:`BlockAllocator.alloc` when the pool cannot
    serve the request — the engine's admission backpressure signal."""


class BlockAllocator:
    """Host-side refcounted free-list over the device block pool.

    Paged allocation has no external fragmentation: any free block can
    serve any sequence, so ``can_alloc(n)`` is simply ``n <= n_free``.
    The plain free list is LIFO so recently-retired blocks (likely
    still warm in cache/HBM pages) are reused first, and allocation
    order is deterministic for tests.

    Three disjoint states partition the non-null blocks:

    * **live** — refcount >= 1 (``alloc`` hands out refcount-1 blocks;
      :meth:`acquire_cached` revives or shares them). Counted by
      ``n_used``.
    * **cached** — refcount 0 but content-addressed: parked in an LRU
      pool, still indexed by hash, revivable for free.
    * **free** — refcount 0, no retained content.

    ``n_free`` counts free + cached (both are allocatable); ``alloc``
    drains the plain free list first and only then evicts the
    least-recently-used cached blocks (``evictions`` counts those).
    Eviction can never touch a block with live references — only
    refcount-0 blocks enter the LRU pool.
    """

    def __init__(self, n_blocks: int, block_size: int):
        if n_blocks < 2:
            raise ValueError(
                f"need at least 2 blocks (1 usable + null), got {n_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.n_blocks = n_blocks
        self.block_size = block_size
        # Block 0 is the null sink — never handed out.
        self._free: List[int] = list(range(n_blocks - 1, 0, -1))
        self._refs: Dict[int, int] = {}          # live block -> refcount
        # refcount-0 cached blocks, LRU order (oldest first = evicted
        # first); value is the block's content hash.
        self._lru: "collections.OrderedDict[int, bytes]" = \
            collections.OrderedDict()
        self._hash_of_block: Dict[int, bytes] = {}
        self._block_of_hash: Dict[bytes, int] = {}
        self._high_water = 0
        # Prefix-cache observability (block granularity; the engine
        # layers token-granularity hit rate on top in ServeMetrics).
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.evictions = 0

    @property
    def n_free(self) -> int:
        """Allocatable blocks: truly free + cached (refcount 0)."""
        return len(self._free) + len(self._lru)

    @property
    def n_used(self) -> int:
        return len(self._refs)

    @property
    def n_cached(self) -> int:
        """Refcount-0 blocks still holding indexed content (the LRU
        pool a future prefix hit can revive for free)."""
        return len(self._lru)

    @property
    def high_water(self) -> int:
        """Peak concurrent blocks in use (capacity-planning stat)."""
        return self._high_water

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache entries."""
        return -(-max(n_tokens, 0) // self.block_size)

    def can_alloc(self, n: int) -> bool:
        return n <= self.n_free

    def alloc(self, n: int) -> List[int]:
        if n > self.n_free:
            raise OutOfBlocks(
                f"requested {n} KV blocks, {self.n_free} free "
                f"({len(self._lru)} of them cached; pool "
                f"{self.n_blocks - 1} x {self.block_size} tokens)")
        out: List[int] = []
        for _ in range(n):
            if self._free:
                b = self._free.pop()
            else:
                # Allocation pressure: forget the least-recently-used
                # cached block. Only refcount-0 blocks live here, so
                # eviction can never reclaim a referenced block.
                b, h = self._lru.popitem(last=False)
                del self._hash_of_block[b]
                del self._block_of_hash[h]
                self.evictions += 1
            self._refs[b] = 1
            out.append(b)
        self._high_water = max(self._high_water, len(self._refs))
        return out

    def peek(self, h: bytes) -> Optional[int]:
        """Non-mutating lookup: the block published under ``h`` (live
        or cached), or None. No refcount, no hit/miss counting, no
        LRU reordering — what admission uses to size its reservation
        before committing, so a backpressure retry loop doesn't
        inflate the cache stats or churn eviction order."""
        return self._block_of_hash.get(h)

    def acquire_cached(self, h: bytes) -> Optional[int]:
        """Prefix-cache lookup: if a block is published under ``h``,
        take a reference on it (reviving it from the LRU pool if it
        was refcount 0) and return its id; else record a miss and
        return None."""
        b = self._block_of_hash.get(h)
        if b is None:
            self.prefix_misses += 1
            return None
        if b in self._lru:
            del self._lru[b]
            self._refs[b] = 1
            self._high_water = max(self._high_water, len(self._refs))
        else:
            self._refs[b] += 1
        self.prefix_hits += 1
        return b

    def register(self, block: int, h: bytes) -> bool:
        """Publish a live, full, immutable ``block`` under content hash
        ``h``. Returns False (no-op) if ``h`` is already published —
        two sequences racing to prefill the same prefix both keep
        their private block; the first registration wins and the
        loser's copy stays anonymous (returns to the plain free list
        on release)."""
        if block not in self._refs:
            raise ValueError(
                f"registering block {block} with no live reference")
        if h in self._block_of_hash:
            return False
        if block in self._hash_of_block:
            raise ValueError(f"block {block} already registered")
        self._hash_of_block[block] = h
        self._block_of_hash[h] = block
        return True

    def verify_integrity(self) -> None:
        """Full-pool invariant check (the randomized property tests'
        probe — e.g. the speculative-rollback machine calls it after
        every trace): live / cached / free partition the non-null
        blocks exactly, refcounts are positive, the content index is a
        bijection, and every cached block is indexed. Raises
        ``AssertionError`` on any violation."""
        live, cached, free = (set(self._refs), set(self._lru),
                              set(self._free))
        assert len(self._free) == len(free), "duplicate free-list entry"
        assert not (live & cached) and not (live & free) \
            and not (cached & free), "block in two states"
        assert live | cached | free == set(range(1, self.n_blocks)), \
            "live/cached/free do not partition the pool"
        assert all(r > 0 for r in self._refs.values()), \
            "zero/negative refcount held as live"
        assert len(self._block_of_hash) == len(self._hash_of_block), \
            "content index out of sync"
        for b, h in self._hash_of_block.items():
            assert self._block_of_hash[h] == b, "index not a bijection"
        for b in cached:
            assert b in self._hash_of_block, "anonymous block in LRU"

    def free(self, blocks: List[int]) -> None:
        """Drop one reference per listed block. A block whose refcount
        reaches 0 parks in the LRU cache pool if it was registered
        (revivable by a future prefix hit), else returns to the plain
        free list."""
        seen = set()
        for b in blocks:
            if not 0 < b < self.n_blocks:
                raise ValueError(f"freeing invalid block id {b}")
            if b not in self._refs or b in seen:
                raise ValueError(f"double free of block {b}")
            seen.add(b)
        # Validate-all-then-mutate: the pool is untouched on error.
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b]:
                continue
            del self._refs[b]
            h = self._hash_of_block.get(b)
            if h is None:
                self._free.append(b)
            else:
                self._lru[b] = h        # most-recently-released last


@dataclasses.dataclass
class KVCache:
    """Device-side paged cache: one K and one V array per model,
    layer-stacked on the leading dim to match the transformer's
    scan-over-layers parameter layout."""

    k: Any  # [L, n_blocks, block_size, Hkv, Dh]
    v: Any  # [L, n_blocks, block_size, Hkv, Dh]
    block_size: int
    n_blocks: int

    @property
    def max_blocks_per_seq(self) -> int:
        # Shapes are static per engine: table width is the worst case.
        return self.n_blocks


def init_kv_cache(cfg, n_blocks: int, block_size: int,
                  mesh: Optional[Any] = None,
                  dtype: Optional[Any] = None) -> KVCache:
    """Allocate the zeroed block pool on device.

    With a mesh, KV heads are sharded over ``tp`` (matching the
    tp-sharded ``wk``/``wv`` projections so the decode step's cache
    writes stay local to each tp shard — no resharding on the hot
    loop, the EQuARX-motivated property of keeping collectives on ICI
    inside the jitted step).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    dtype = dtype or cfg.dtype
    shape = (cfg.n_layers, n_blocks, block_size, cfg.n_kv_heads,
             cfg.head_dim)
    sharding = None
    if mesh is not None:
        tp = mesh.shape.get("tp", 1)
        if tp > 1 and cfg.n_kv_heads % tp == 0:
            sharding = NamedSharding(mesh, P(None, None, None, "tp", None))
    def zeros():
        return jnp.zeros(shape, dtype)
    if sharding is not None:
        k = jax.jit(zeros, out_shardings=sharding)()
        v = jax.jit(zeros, out_shardings=sharding)()
    else:
        k, v = zeros(), zeros()
    return KVCache(k=k, v=v, block_size=block_size, n_blocks=n_blocks)


def pick_bucket(n: int, buckets: Tuple[int, ...]) -> int:
    """Smallest bucket >= n (buckets ascending). Bucketing pads batch
    and prompt shapes to a short menu of sizes so the jit cache stays
    small and hot — the no-per-request-recompilation invariant."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} exceeds largest bucket {buckets[-1]}")


def page_chunks(n_pages: int, chunk_pages: int) -> List[Tuple[int, int]]:
    """Block-aligned ``[start, stop)`` ranges covering ``n_pages`` KV
    pages in ``chunk_pages``-sized pieces (last one ragged). This is
    the one chunking function shared by the direct-migration stream
    (worker side), chunked inject (engine side), and the Python cost
    twin — all three must agree on the chunk boundaries or the
    scheduled cost describes a transfer that never happens."""
    if n_pages < 0:
        raise ValueError(f"n_pages {n_pages} < 0")
    if chunk_pages < 1:
        raise ValueError(f"chunk_pages {chunk_pages} < 1")
    return [(lo, min(lo + chunk_pages, n_pages))
            for lo in range(0, n_pages, chunk_pages)]
