"""Replica worker process: one ``ServeEngine`` behind the RPC seam.

``python -m horovod_tpu.serve.worker --port 0`` (or the
``bin/hvd-serve-worker`` wrapper) listens on a TCP port, announces
``HVD-SERVE-WORKER ready port=<p> pid=<pid>`` on stdout, accepts ONE
router connection, and serves the engine seam over
:mod:`horovod_tpu.serve.rpc` until the router disconnects or sends
``shutdown``. The engine itself is untouched: every replica invariant
the in-process fleet pins (bitwise decode parity, allocator safety,
backpressure) holds because the worker runs exactly the same
``ServeEngine`` code the router would have run in-process.

The worker builds its own params deterministically from the model
config plus a seed (``init_transformer(cfg, PRNGKey(seed))``), so the
router never ships multi-GB weights over the control channel; router
and workers agree on the model by construction (documented contract —
see docs/serving.md "Cross-process fleet").

Heartbeats are pull-based: the router's ``step``/``heartbeat`` RPCs
both return one *beat* payload — the admission state, the full
``ServeMetrics`` snapshot (so the router-process Prometheus scrape
spans worker processes), the latency samples recorded since the last
beat (delta-shipped, bounded), and every newly-finished result, each
timestamp re-anchored as an age relative to this process's clock
(``perf_counter`` epochs are per-process). Liveness is the transport
itself: a worker that dies mid-anything fails the router's next RPC.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from horovod_tpu.serve.kv_cache import page_chunks
from horovod_tpu.serve.rpc import (
    RpcConn, WORKER_READY_PREFIX, handoff_from_wire,
    handoff_meta_from_wire, handoff_meta_to_wire, handoff_to_wire,
    serve_connection,
)


#: Max in-flight (unanswered) frames on a pipelined peer stream.
#: Replies are tiny dicts, so the window exists only to bound the
#: reply backlog — it must comfortably exceed the chunk counts real
#: plans produce, or the pipeline degrades to lockstep.
_PEER_WINDOW = 8


def _build_engine(model_cfg: Dict[str, Any], serve_cfg: Dict[str, Any],
                  seed: int, instance: str):
    """Materialize the engine from wire-shaped configs (the inverse of
    ``rpc.model_cfg_to_wire``/``serve_cfg_to_wire``)."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.compression import Compression
    from horovod_tpu.models import TransformerConfig, init_transformer
    from horovod_tpu.serve.engine import ServeConfig, ServeEngine

    mc = dict(model_cfg)
    mc["dtype"] = getattr(jnp, mc["dtype"])
    cfg = TransformerConfig(**mc)
    params = init_transformer(cfg, jax.random.PRNGKey(seed))
    sc = dict(serve_cfg)
    if sc.get("cache_dtype") is not None:
        sc["cache_dtype"] = getattr(jnp, sc["cache_dtype"])
    comp = sc.get("compression")
    sc["compression"] = (None if comp in (None, "none")
                         else getattr(Compression, comp))
    for k in ("batch_buckets", "prefill_buckets"):
        if sc.get(k) is not None:
            sc[k] = tuple(sc[k])
    draft = sc.get("draft")
    if draft is not None:
        # Speculative sub-config: the worker rebuilds the draft model
        # from (config, seed) exactly like it rebuilds the target —
        # the engine's SpecDecoder does the init, so a cross-process
        # speculative fleet agrees on the draft by construction.
        from horovod_tpu.serve.speculative import DraftConfig
        dmc = dict(draft["model_cfg"])
        dmc["dtype"] = getattr(jnp, dmc["dtype"])
        sc["draft"] = DraftConfig(
            TransformerConfig(**dmc), seed=int(draft["seed"]),
            cache_dtype=(None if draft["cache_dtype"] is None
                         else getattr(jnp, draft["cache_dtype"])))
    return ServeEngine(cfg, params, ServeConfig(**sc),
                       instance=instance)


class ReplicaWorker:
    """The dispatch table over one engine. Process-agnostic by design:
    :func:`main` runs it behind a listening socket, and the tier-1
    tests run it in a thread over a socketpair (same dispatch, same
    marshalling, no spawn cost) — only the slow tier pays real
    processes."""

    def __init__(self, conn: RpcConn, clock=time.perf_counter,
                 peer_host: str = "127.0.0.1"):
        self.conn = conn
        self.engine = None
        self._clock = clock
        # Delta cursors: each beat ships only samples recorded since
        # the previous one (heartbeats stay O(step work), never
        # O(lifetime)).
        self._ft_cursor = 0
        self._pt_cursor = 0
        # Direct-migration bulk plane (docs/serving.md "Direct
        # migration"): a second listener peers dial to stream KV pages
        # point-to-point, served on daemon threads. The engine is
        # single-threaded by design, so EVERY engine touch — router
        # verbs and peer streams alike — serializes on this lock
        # (per-worker, so concurrent cross-worker streams can never
        # form a lock cycle: nobody holds their own lock while waiting
        # on a peer's).
        self._lock = threading.RLock()
        self._peer_host = peer_host
        self._peer_lsock = None
        self.peer_port = 0
        # Manifest epochs ever begun here: a replayed epoch (a retried
        # partial stream) is refused — each migration attempt gets a
        # fresh epoch from the router, so stale partials can neither
        # resume nor double-inject.
        self._peer_epochs: set = set()
        # Outbound bulk connections, keyed by (host, port) and reused
        # across migrations — the dial handshake would otherwise
        # dominate small moves. Only the dispatch thread touches this
        # (migrate_to / shutdown run on the router's serialized verb
        # loop). A conn that fails mid-stream is dropped, not retried.
        self._peer_conns: Dict[Any, RpcConn] = {}

    # -- handlers ----------------------------------------------------

    def configure(self, model_cfg, serve_cfg, seed, instance,
                  kv_codec=0):
        """(Re)build the engine. A second configure replaces the
        engine with a fresh one (same process, same jit cache via the
        ``make_serve_fns`` memo) — the bench's cold-fleet-per-pass
        protocol without a respawn. ``kv_codec`` sets the span codec
        for THIS side's replies (the export path's K/V pages)."""
        self.engine = _build_engine(model_cfg, serve_cfg, int(seed),
                                    str(instance))
        self.conn.codec = int(kv_codec)
        self._ft_cursor = self._pt_cursor = 0
        self._ensure_peer_listener()
        return {"n_blocks": self.engine.allocator.n_blocks,
                "block_size": self.engine.cfg.block_size,
                "pid": os.getpid(),
                "peer_port": self.peer_port,
                "beat": self._beat()}

    def _require_engine(self):
        if self.engine is None:
            raise RuntimeError("worker not configured yet")
        return self.engine

    def _result_to_wire(self, res, now: float) -> Dict[str, Any]:
        def age(t):
            return None if t is None else now - t

        return {
            "rid": res.rid, "status": res.status,
            "http_status": res.http_status, "tokens": list(res.tokens),
            "n_prompt": res.n_prompt,
            "age_submitted": age(res.submitted_at),
            "age_first_token": age(res.first_token_at),
            "age_finished": age(res.finished_at),
            "reason": res.reason, "deadline_class": res.deadline_class,
            "retry_after_s": res.retry_after_s,
        }

    def _beat(self) -> Dict[str, Any]:
        eng = self._require_engine()
        now = self._clock()
        m = eng.metrics
        ft = [float(x) for x in m.first_token_s[self._ft_cursor:]]
        pt = [float(x) for x in m.per_token_s[self._pt_cursor:]]
        self._ft_cursor += len(ft)
        self._pt_cursor += len(pt)
        # DRAIN finished results into the beat (pop, don't copy): the
        # router is the only consumer — it caches them its side and
        # never re-queries — so shipping is exactly-once by
        # construction, each beat costs O(newly finished), and a
        # long-lived worker's result map stays bounded instead of
        # accumulating every token list it ever served.
        results = {}
        for rid in list(eng._results):
            results[rid] = self._result_to_wire(eng._results.pop(rid),
                                                now)
        return {
            "pending": eng.pending,
            "kv_blocks_free": eng.allocator.n_free,
            "snap": m.snapshot(),
            "ft": ft, "pt": pt,
            "results": results,
            # This worker's clock at beat time: the router brackets
            # the heartbeat RPC and estimates the cross-process clock
            # offset from the RTT midpoint (docs/observability.md
            # "One timebase").
            "now": now,
        }

    def heartbeat(self):
        return self._beat()

    def step(self):
        eng = self._require_engine()
        if eng.pending:
            eng.step()
        return self._beat()

    def admission_snapshot(self):
        return self._require_engine().admission_snapshot()

    def cached_chain_len(self, chain):
        return self._require_engine().cached_chain_len(
            [bytes(c) for c in chain])

    def submit(self, prompt, max_new_tokens=None, deadline_in=None,
               deadline_class=0, prefill_only=False, chain=None):
        eng = self._require_engine()
        deadline = (None if deadline_in is None
                    else self._clock() + float(deadline_in))
        return eng.submit(
            [int(t) for t in prompt], max_new_tokens=max_new_tokens,
            deadline=deadline, deadline_class=int(deadline_class),
            prefill_only=bool(prefill_only),
            chain=[bytes(c) for c in chain] if chain is not None
            else None,
            # The distributed trace id rides the v2 frame header, not
            # the payload — the recv loop parked it on the conn.
            trace_id=self.conn.last_trace_id)

    def withdraw(self, rid):
        return self._require_engine().withdraw(int(rid))

    def handoff_ready(self):
        return self._require_engine().handoff_ready()

    def export_prefilled(self, rid):
        eng = self._require_engine()
        return handoff_to_wire(eng.export_prefilled(int(rid)),
                               self._clock())

    def inject_prefilled(self, wire_handoff):
        eng = self._require_engine()
        return eng.inject_prefilled(
            handoff_from_wire(wire_handoff, self._clock()))

    def running_exportable(self):
        return self._require_engine().running_exportable()

    def export_running(self, rid):
        eng = self._require_engine()
        return handoff_to_wire(eng.export_running(int(rid)),
                               self._clock())

    def export_trace(self):
        """This replica's chrome-trace events plus the timebase anchor
        (``trace_metadata``) — the router's ``export_fleet_trace``
        collects one of these per worker and stamps its RTT-estimated
        clock offset into the metadata so ``bin/hvd-trace merge`` can
        put every span on the router's clock."""
        m = self._require_engine().metrics
        return {"events": list(m._events),
                "meta": m.trace_metadata(worker_pid=os.getpid())}

    def shutdown(self):
        if self._peer_lsock is not None:
            try:
                self._peer_lsock.close()
            except OSError:
                pass
            self._peer_lsock = None
        for conn in self._peer_conns.values():
            conn.close()
        self._peer_conns.clear()
        return {"pid": os.getpid()}

    # -- direct migration (worker <-> worker bulk plane) --------------

    def _ensure_peer_listener(self) -> None:
        """Start the bulk listener peers stream KV pages to (lazy, on
        first configure — a worker that never joins a fleet binds
        nothing). Failure to bind degrades cleanly: ``peer_port``
        stays 0 and the router keeps this replica on the relayed
        path."""
        if self._peer_lsock is not None:
            return
        import socket

        try:
            ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            ls.bind((self._peer_host, 0))
            ls.listen(8)
        except OSError:
            self.peer_port = 0
            return
        self._peer_lsock = ls
        self.peer_port = ls.getsockname()[1]
        threading.Thread(target=self._peer_accept_loop, args=(ls,),
                         daemon=True).start()

    def _peer_accept_loop(self, lsock) -> None:
        import socket

        while True:
            try:
                sock, _addr = lsock.accept()
            except OSError:
                return   # listener closed (shutdown)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_peer, args=(sock,),
                             daemon=True).start()

    def _serve_peer(self, sock) -> None:
        """One inbound page stream: ``peer_begin`` (manifest; reserves
        blocks), N x ``peer_chunk`` (scatter), ``peer_commit``
        (materialize, reply the new rid). The staging token is
        CONNECTION-LOCAL: if the stream dies before commit — source
        SIGKILLed mid-transfer, reset, anything — the finally aborts
        the staged inject and the partial pages are discarded, so the
        target never holds a half sequence (the router's exactly-once
        requeue handles the request side)."""
        conn = RpcConn(sock)
        state: Dict[str, Any] = {"token": None}

        def peer_begin(epoch, meta):
            with self._lock:
                eng = self._require_engine()
                if int(epoch) in self._peer_epochs:
                    raise ValueError(
                        f"migration manifest epoch {epoch} already "
                        "seen — stale partial stream replayed")
                self._peer_epochs.add(int(epoch))
                state["token"] = eng.inject_begin(
                    handoff_meta_from_wire(meta, self._clock()))
            return True

        def peer_chunk(k_pages, v_pages):
            with self._lock:
                return self._require_engine().inject_chunk(
                    state["token"], k_pages, v_pages)

        def peer_commit():
            with self._lock:
                rid = self._require_engine().inject_commit(
                    state["token"])
            state["token"] = None
            return rid

        try:
            serve_connection(conn, {
                "peer_begin": peer_begin,
                "peer_chunk": peer_chunk,
                "peer_commit": peer_commit,
            })
        finally:
            token = state["token"]
            if token is not None:
                with self._lock:
                    try:
                        if self.engine is not None:
                            self.engine.inject_abort(token)
                    except Exception:
                        pass
            conn.close()

    def _peer_conn(self, host, port) -> Optional[RpcConn]:
        """Cached outbound bulk connection, dialed on first use —
        reused across migrations to the same peer (the TCP handshake
        would otherwise dominate small moves). ``None`` when the dial
        fails: the caller reports ``dial_failed`` and the router keeps
        the relayed path. A cached conn that dies mid-stream is
        dropped by :meth:`migrate_to`, never retried here."""
        import socket

        key = (str(host), int(port))
        conn = self._peer_conns.get(key)
        if conn is not None:
            return conn
        try:
            psock = socket.create_connection(key, timeout=30.0)
        except OSError:
            return None
        psock.settimeout(None)
        psock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn = RpcConn(psock)
        self._peer_conns[key] = conn
        return conn

    def migrate_to(self, kind, erid, host, port, chunk_pages, epoch):
        """Router control frame of the direct plane: export ``erid``
        and stream its pages straight to the peer worker at ``(host,
        port)`` — the router never touches the bulk bytes. Dial-first:
        a failed dial returns ``dial_failed`` with the sequence
        untouched (router falls back to relayed); a stream that dies
        AFTER export returns ``failed`` (pages are gone on both sides
        — router requeues the request, the exactly-once path). The
        engine lock is held only for the export; the wire streaming
        runs lock-free off the exported copies.

        The chunk stream is PIPELINED: begin + chunk frames are
        written without waiting for replies (at most ``_PEER_WINDOW``
        outstanding — replies are tiny, the window only bounds the
        reply backlog so a stalled target can never deadlock the
        socket buffers against us), and only ``peer_commit`` is a
        full round trip. With the cached dial this makes a move cost
        ~one traversal of the pages plus one RTT — the whole claim of
        the direct plane over the relayed two-traversal path."""
        eng = self._require_engine()
        if kind not in ("prefilled", "running"):
            raise ValueError(f"unknown migration kind {kind!r}")
        t0 = self._clock()
        peer = self._peer_conn(host, port)
        if peer is None:
            return {"status": "dial_failed",
                    "error": f"dial {host}:{port} failed"}
        # The router conn's codec is already the native id: the bulk
        # stream ships pages under the same wire codec the relayed
        # export would have. Byte counters are per-conn cumulative, so
        # this move's contribution is a delta.
        peer.codec = int(self.conn.codec)
        raw0, wire0 = peer.span_raw_bytes, peer.span_wire_bytes
        with self._lock:
            h = (eng.export_prefilled(int(erid))
                 if kind == "prefilled"
                 else eng.export_running(int(erid)))
        try:
            pending = 1
            peer.call_begin("peer_begin", epoch=int(epoch),
                            meta=handoff_meta_to_wire(h, self._clock()))
            for lo, hi in page_chunks(h.n_pages, int(chunk_pages)):
                peer.call_begin(
                    "peer_chunk",
                    np.ascontiguousarray(h.k_pages[:, lo:hi]),
                    np.ascontiguousarray(h.v_pages[:, lo:hi]))
                pending += 1
                while pending > _PEER_WINDOW:
                    peer.call_finish()
                    pending -= 1
            while pending:
                peer.call_finish()
                pending -= 1
            new_erid = int(peer.call("peer_commit"))
        except Exception as e:   # noqa: BLE001 — stream died mid-move
            self._peer_conns.pop((str(host), int(port)), None)
            peer.close()
            return {"status": "failed",
                    "error": f"{type(e).__name__}: {e}"}
        return {"status": "ok", "erid": new_erid,
                "raw_bytes": peer.span_raw_bytes - raw0,
                "wire_bytes": peer.span_wire_bytes - wire0,
                "ms": (self._clock() - t0) * 1e3}

    # -- loop --------------------------------------------------------

    def handlers(self) -> Dict[str, Any]:
        def locked(fn):
            def call(*args, **kwargs):
                with self._lock:
                    return fn(*args, **kwargs)
            return call

        out = {
            "configure": self.configure,
            "heartbeat": self.heartbeat,
            "step": self.step,
            "admission_snapshot": self.admission_snapshot,
            "cached_chain_len": self.cached_chain_len,
            "submit": self.submit,
            "withdraw": self.withdraw,
            "handoff_ready": self.handoff_ready,
            "export_prefilled": self.export_prefilled,
            "inject_prefilled": self.inject_prefilled,
            "running_exportable": self.running_exportable,
            "export_running": self.export_running,
            "export_trace": self.export_trace,
            "shutdown": self.shutdown,
        }
        # Peer streams touch the same engine from their own threads,
        # so every router verb serializes on the worker lock —
        # EXCEPT migrate_to, which locks only its export internally
        # (holding the lock across the wire stream would stall peer
        # injects for the whole transfer for no correctness gain).
        out = {m: locked(fn) for m, fn in out.items()}
        out["migrate_to"] = self.migrate_to
        out["__closing__"] = ("shutdown",)
        return out

    def serve(self) -> None:
        serve_connection(self.conn, self.handlers())


def main(argv: Optional[List[str]] = None) -> int:
    import socket

    ap = argparse.ArgumentParser(
        description="horovod_tpu serve worker: one ServeEngine replica "
                    "behind the fleet RPC seam (see docs/serving.md)")
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address (default loopback; the RPC "
                         "channel is unauthenticated — keep it on a "
                         "trusted network)")
    ap.add_argument("--port", type=int, default=0,
                    help="listen port (0 = ephemeral, announced on "
                         "stdout)")
    args = ap.parse_args(argv)

    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind((args.host, args.port))
    lsock.listen(1)
    port = lsock.getsockname()[1]
    print(f"{WORKER_READY_PREFIX} port={port} pid={os.getpid()}",
          flush=True)
    sock, _addr = lsock.accept()
    lsock.close()
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    ReplicaWorker(RpcConn(sock), peer_host=args.host).serve()
    return 0


if __name__ == "__main__":
    sys.exit(main())
