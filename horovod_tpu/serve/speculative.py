"""Speculative decoding: a draft transformer proposes, the target
verifies.

Decode is latency-bound exactly where a draft model pays (the MLPerf
TPU recipes, PAPERS.md): at small batch each decode step streams the
whole target model's weights to emit ONE token per sequence. A small
draft model proposing ``k`` tokens lets the target *verify* all k in
one chunked step — the weights stream once per k tokens instead of
once per token — and under greedy decoding the accepted stream is
**bitwise identical** to plain decode (pinned by
tests/test_speculative.py), because verification compares the target's
own argmax at every proposed position and keeps exactly the longest
matching prefix.

The draft is an ordinary transformer behind the same serve machinery:

* its jitted step fns come from the same :func:`~horovod_tpu.serve.
  decode.make_serve_fns` memo (a draft sharing the target's config —
  the all-accept test rig — shares the target's compiled programs);
* it keeps its own paged KV pool with the target's block geometry and
  the same bucketed decode discipline — one draft decode call per
  proposed token, batched across the active sequences;
* its params are built deterministically from
  ``init_transformer(draft.model_cfg, PRNGKey(draft.seed))`` — the
  same params-from-seed contract the RPC workers use for the target,
  so a cross-process speculative fleet agrees on the draft by
  construction.

**The verify step is a chunked prefill over reserved pages** (the
PR 4 plumbing): the target runs ``[last_token, d1..d_{k-1}]`` through
:func:`~horovod_tpu.serve.decode.make_serve_fns`'s ``verify`` fn —
token-granularity page writes through the sequence's existing block
table, attention over all its pages under the global-position causal
mask — and emits its own argmax at every chunk position. Acceptance is
host-side and greedy-exact: ``d_{j+1}`` is accepted iff it equals the
target argmax after ``d_j``; the first mismatch contributes the
target's own token instead (the correction token — worst case one
token per round, exactly plain decode's progress). When all k match,
the round emits the k draft tokens and no bonus token: forgoing the
(k+1)-th "free" token keeps the draft's KV cursor in lockstep with the
target's (no catch-up feed next round), which keeps every round's
shape uniform and the whole scheme simple enough to pin.

**Rollback is a cursor rewind.** The verify step wrote K/V for every
chunk position, accepted or not; rejected positions simply stay beyond
the sequence's length cursor (``_Seq.n_cached``) — the block table is
untouched, no page is scrubbed, and the garbage is overwritten by
later writes before the cursor ever reaches it (attention masks by
position, so it is never read meanwhile). The randomized property
test drives exactly this: adversarial drafts that force rejections at
every accept length, with streams pinned bitwise against plain decode
and the allocator's integrity checked every round.

Under sampling, the acceptance rule generalizes to rejection sampling
(accept ``d`` with probability ``min(1, p_target/p_draft)``, resample
from the normalized residual on rejection), which preserves the target
distribution exactly; this engine is greedy-only, where rejection
sampling degenerates to the exact-match rule above — token-for-token
parity, the property the tests pin.

Reference analog: none — the reference framework is training-only.
Design follows the standard speculative-decoding construction
(Leviathan et al.; vLLM/TGI implementations) specialized to greedy.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu.serve.kv_cache import BlockAllocator, pick_bucket


@dataclasses.dataclass(frozen=True)
class DraftConfig:
    """The ``ServeConfig.draft`` sub-config: which draft transformer to
    run and how to build its params. The draft inherits the engine's
    block geometry (block size, table width, buckets) — only the model
    differs."""

    model_cfg: Any               # TransformerConfig of the draft model
    # Draft params are init_transformer(model_cfg, PRNGKey(seed)) —
    # the same deterministic params-from-seed contract the RPC workers
    # use for the target, so every replica (local or remote) builds
    # the identical draft.
    seed: int = 0
    cache_dtype: Any = None      # draft KV dtype (default: draft dtype)


def accept_greedy(proposals: Sequence[int],
                  verified: Sequence[int]) -> Tuple[int, List[int]]:
    """The greedy acceptance rule, host-side and pure (unit-tested
    directly): ``proposals`` are the draft's k tokens ``d1..dk``,
    ``verified`` the target's argmax after each of
    ``[t0, d1..d_{k-1}]``. Returns ``(n_accepted, emitted)`` where
    ``emitted`` is the longest matching draft prefix plus — on the
    first mismatch — the target's own correction token. All-match
    emits exactly the k draft tokens (no bonus token; see module
    docstring)."""
    k = len(proposals)
    assert len(verified) == k
    emitted: List[int] = []
    for j in range(k):
        if proposals[j] == verified[j]:
            emitted.append(proposals[j])
        else:
            emitted.append(verified[j])
            return j, emitted
    return k, emitted


@dataclasses.dataclass
class _DraftSeq:
    """Draft-side state for one active sequence: its mirror block
    reservation in the draft pool. The KV cursor is not stored — it is
    the engine sequence's ``n_cached`` by the lockstep invariant the
    acceptance rule maintains (module docstring)."""

    blocks: List[int]
    table: np.ndarray            # [table_width] int32, draft pool ids


class SpecDecoder:
    """The speculative side-car of one :class:`ServeEngine`: draft
    params + paged KV pool + the propose/verify/accept round that
    replaces ``_decode_once`` when ``ServeConfig.draft`` is set.

    Owns no scheduling: the engine still admits, prefills, retires and
    exposes results exactly as before — only the decode iteration is
    swapped, which is why every engine invariant (handoff, drain
    export, backpressure) composes with speculation unchanged.
    """

    def __init__(self, engine):
        import jax

        from horovod_tpu.models import init_transformer
        from horovod_tpu.serve import decode as decode_lib
        from horovod_tpu.serve.kv_cache import init_kv_cache

        self._eng = engine
        cfg = engine.cfg
        draft: DraftConfig = cfg.draft
        dm = draft.model_cfg
        tm = engine.model_cfg
        if dm.vocab_size != tm.vocab_size:
            raise ValueError(
                f"draft vocab {dm.vocab_size} != target vocab "
                f"{tm.vocab_size} — proposals are token ids and must "
                "share one vocabulary")
        self.k = cfg.spec_k
        self._params = init_transformer(dm, jax.random.PRNGKey(draft.seed))
        bs = cfg.block_size
        self._bs = bs
        self._width = engine._table_width
        # Mirror pool sized for the draft's OWN worst case: it only
        # ever holds ACTIVE (batch-slotted) sequences' full private
        # reservations — at most max_batch x table_width blocks — so
        # any batch the target admitted is reservable here by
        # construction. Mirroring the target's n_blocks instead would
        # break under prefix caching: the target's pool admits batches
        # whose shared prefix blocks are refcounted once, while the
        # draft (no content index) pays every sequence's full width.
        n_blocks = cfg.max_batch * self._width + 1
        self.allocator = BlockAllocator(n_blocks, bs)
        self.cache = init_kv_cache(dm, n_blocks, bs, mesh=engine.mesh,
                                   dtype=draft.cache_dtype)
        # The draft shares the make_serve_fns memo: a draft configured
        # with the target's own model config (the all-accept rig)
        # reuses the target's compiled programs outright.
        (self._prefill_fn, self._resume_fn, self._decode_fn, _inject,
         _verify) = decode_lib.make_serve_fns(
             dm, engine.mesh, block_size=bs, table_width=self._width,
             compression=cfg.compression)
        self._seqs: Dict[int, _DraftSeq] = {}

    # -- per-sequence lifecycle ---------------------------------------

    def drop(self, rid: int) -> None:
        """Release the draft-side reservation of a retired, exported,
        or migrated sequence. No-op for sequences the draft never saw
        (prefill-only parks, withdrawn queue entries)."""
        st = self._seqs.pop(rid, None)
        if st is not None:
            self.allocator.free(st.blocks)

    def _ensure(self, seq) -> _DraftSeq:
        """Draft-side catch-up for a sequence the draft has no state
        for (fresh prefill completion, or a handoff/migration injected
        mid-decode): reserve mirror blocks and prefill the draft's KV
        for every position the target has cached — the full stream
        ``(prompt + generated)[:n_cached]``, chunked block-aligned
        through the engine's prefill buckets. The draft always
        prefills the whole stream itself (the target's prefix cache
        may have skipped prompt FLOPs the draft never ran)."""
        st = self._seqs.get(seq.rid)
        if st is not None:
            return st
        need = self.allocator.blocks_for_tokens(
            len(seq.prompt) + seq.max_new)
        blocks = self.allocator.alloc(need)
        table = np.zeros(self._width, np.int32)
        table[:len(blocks)] = blocks
        st = _DraftSeq(blocks=blocks, table=table)
        self._seqs[seq.rid] = st
        stream = (list(seq.prompt) + list(seq.generated))[:seq.n_cached]
        buckets = self._eng._prefill_buckets
        chunk_cap = max(buckets)
        off = 0
        while off < len(stream):
            chunk = min(len(stream) - off, chunk_cap)
            if off + chunk < len(stream):
                chunk -= chunk % self._bs   # non-final chunks stay
                #                             block-aligned for resume
            toks = np.zeros(pick_bucket(chunk, buckets), np.int32)
            toks[:chunk] = stream[off:off + chunk]
            kc, vc, _tok = self._resume_fn(
                self._params, self.cache.k, self.cache.v, toks,
                np.int32(off), np.int32(chunk), st.table)
            self.cache.k, self.cache.v = kc, vc
            off += chunk
        return st

    # -- the round ----------------------------------------------------

    def round(self) -> None:
        """One speculative iteration for the engine's active batch:
        k batched draft decode steps propose, one target verify step
        checks, host-side acceptance emits 1..k tokens per sequence
        and rewinds past rejected positions (cursor-only rollback)."""
        import jax

        eng = self._eng
        active = eng._active
        if not active:
            return
        n = len(active)
        bucket = pick_bucket(n, eng._batch_buckets)
        states = [self._ensure(s) for s in active]

        # -- propose: k draft decode steps, batched over the batch ----
        d_tables = np.zeros((bucket, self._width), np.int32)
        for i, st in enumerate(states):
            d_tables[i] = st.table
        frontier = np.zeros(bucket, np.int32)
        positions = np.zeros(bucket, np.int32)
        for i, seq in enumerate(active):
            frontier[i] = seq.last_token
            positions[i] = seq.n_cached
        proposals = np.zeros((n, self.k), np.int64)
        t0 = eng._clock()
        with jax.profiler.TraceAnnotation("serve:spec_draft"):
            for step in range(self.k):
                kc, vc, out = self._decode_fn(
                    self._params, self.cache.k, self.cache.v, frontier,
                    positions, d_tables)
                out = np.asarray(out)
                self.cache.k, self.cache.v = kc, vc
                proposals[:, step] = out[:n]
                frontier = out.copy()
                positions = positions + 1
        t1 = eng._clock()

        # -- verify: ONE chunked target step over reserved pages ------
        chunk = np.zeros((bucket, self.k), np.int32)
        vpos = np.zeros(bucket, np.int32)
        t_tables = np.zeros((bucket, self._width), np.int32)
        for i, seq in enumerate(active):
            chunk[i, 0] = seq.last_token
            chunk[i, 1:] = proposals[i, :self.k - 1]
            vpos[i] = seq.n_cached
            t_tables[i] = seq.table
        with jax.profiler.TraceAnnotation("serve:spec_verify"):
            kc, vc, ver = eng._verify_fn(
                eng._params, eng.cache.k, eng.cache.v, chunk, vpos,
                t_tables)
            ver = np.asarray(ver)
        t2 = eng._clock()
        eng.cache.k, eng.cache.v = kc, vc

        # -- accept + cursor rollback, host-side ----------------------
        emitted_total = 0
        accepted_total = 0
        proposed_total = 0
        for i, seq in enumerate(active):
            n_acc, emitted = accept_greedy(
                [int(t) for t in proposals[i]],
                [int(t) for t in ver[i]])
            # Plain decode stops at max_new and at the FIRST eos —
            # truncate to match it token-for-token.
            room = seq.max_new - len(seq.generated)
            emitted = emitted[:room]
            if eng.cfg.eos_id is not None and eng.cfg.eos_id in emitted:
                emitted = emitted[:emitted.index(eng.cfg.eos_id) + 1]
            n_acc = min(n_acc, len(emitted))
            # Accept-rate denominator: proposals the target actually
            # JUDGED before the stream ended — the accepted prefix
            # plus, if the round ended on a mismatch that was emitted,
            # the one judged-wrong proposal. Proposals past the
            # max_new cap or past an accepted eos were never
            # emittable: scheduling artifacts, not draft
            # disagreement, and they must not deflate the rate a real
            # draft is judged by.
            proposed_total += n_acc + (1 if n_acc < len(emitted) else 0)
            seq.generated.extend(emitted)
            # The rollback: rejected chunk positions stay past the
            # cursor; table and pool untouched.
            seq.n_cached += len(emitted)
            emitted_total += len(emitted)
            accepted_total += n_acc
        eng.metrics.record_spec_round(
            t0, t1 - t0, t2 - t1, n, eng.cfg.max_batch,
            proposed=proposed_total, accepted=accepted_total,
            emitted=emitted_total,
            traces=[s.trace for s in active if s.trace])


# ---------------------------------------------------------------------------
# Bench/test rig: a target that agrees with its draft by construction
# ---------------------------------------------------------------------------

def make_draft_target_params(draft_cfg, n_layers: int, seed: int = 0,
                             extra_seed: int = 1):
    """Build ``(target_cfg, target_params)`` such that the target is
    ``n_layers`` deep but computes **exactly** the logits of the
    ``draft_cfg`` model initialized from ``seed``: the first
    ``draft_cfg.n_layers`` layers (plus embed / final norm / lm head)
    are the draft's own params, and every extra layer's residual
    out-projections (``wo``, ``w_down``) are zero — a residual layer
    that contributes nothing but costs its full matmuls.

    This is the speculative benchmark's idealized pair: the draft
    agrees with the target at every position (accept rate 1.0), so
    the measured speedup isolates what speculation buys *per accepted
    token* — weights stream once per k tokens — from model-quality
    effects. Real drafts scale the win by their measured accept rate
    (reported alongside). The pair is deterministic from ``seed``, so
    an engine configured with ``DraftConfig(draft_cfg, seed=seed)``
    rebuilds the matching draft by construction."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.models import init_transformer

    if n_layers <= draft_cfg.n_layers:
        raise ValueError(
            f"target n_layers {n_layers} must exceed the draft's "
            f"{draft_cfg.n_layers}")
    target_cfg = dataclasses.replace(draft_cfg, n_layers=n_layers)
    draft_params = init_transformer(draft_cfg, jax.random.PRNGKey(seed))
    target_params = init_transformer(target_cfg,
                                     jax.random.PRNGKey(extra_seed))
    Ld = draft_cfg.n_layers
    layers = {}
    for name, extra_leaf in target_params["layers"].items():
        leaf = jnp.asarray(extra_leaf)
        leaf = leaf.at[:Ld].set(draft_params["layers"][name])
        if name in ("wo", "w_down"):
            leaf = leaf.at[Ld:].set(0)
        layers[name] = leaf
    out = dict(target_params)
    out["layers"] = layers
    for name in ("embed", "final_norm", "lm_head"):
        out[name] = draft_params[name]
    return target_cfg, out
