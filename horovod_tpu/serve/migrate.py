"""Topology-scheduled direct KV-page migration (docs/serving.md
"Direct migration").

The serving fleet's bulk data path — prefill handoffs, migrating
drains, dead-worker recovery — historically relayed KV pages through
the router process: ``export`` pulled the pages to the router,
``inject`` pushed them to the target, two full wire traversals per
migration. This module is the planning half of the direct plane that
removes the router from the bulk path:

* **The knob.** ``HOROVOD_FLEET_DIRECT_MIGRATION`` (sane-env style:
  ``auto`` = dial worker→worker and fall back to relayed when the
  dial fails; ``off`` = the relayed path, byte for byte). The router
  reads it once per fleet via :func:`direct_migration_mode`.
* **The cost twin.** Training collectives get alpha-beta cost
  verdicts from the native schedule interpreter
  (``hvd_algo_cost_us``); a KV migration is a point-to-point stream,
  so its verdict is a two-term closed form over the SAME measured
  model ``hvd.topology()`` publishes. :func:`migration_cost_us` is
  mirrored bit-for-bit by the native ``hvd_migration_cost_us`` export
  (native/src/topology.cc) and the sanitizer tier cross-checks the
  two — the twin exists so router tests can score placements without
  a controller, not so the formulas can drift.
* **The plan.** :func:`plan_migration` turns one candidate move
  (source, target, codec, raw bytes) into a chunk schedule: it sweeps
  a power-of-two chunk menu through the cost model and returns the
  argmin. Chunking pipelines export → wire → inject (the per-chunk
  alpha+ack overhead buys overlap of the final chunk's inject), so
  the model has a genuine interior minimum instead of always
  answering "one big span".

Replica → topology rank: router instances are small decimal strings
(``"0"``, ``"1"``, ...); :func:`replica_rank` maps one onto the
``np``-rank ring the probe measured. On a single-host fleet the model
is usually ``None`` and every cost is 0 — placement then degrades to
the pure least-load pick, pinned by the topology-scored drain test.
"""

from __future__ import annotations

import math
import os
import warnings
from typing import Any, Dict, List, Optional, Tuple

#: The sane-env knob: ``auto`` (default) dials the direct channel and
#: falls back to relayed on a failed dial; ``off`` forces the PR 12
#: relayed path byte-for-byte. Documented in docs/serving.md.
DIRECT_MIGRATION_ENV = "HOROVOD_FLEET_DIRECT_MIGRATION"

#: Python mirror of ``kSpanOverheadUs`` (native/src/topology.cc): the
#: fixed per-span bookkeeping cost the schedule interpreter charges on
#: top of alpha. A migration chunk pays it twice (send + ack).
SPAN_OVERHEAD_US = 0.2


#: Single pin home for the direct-migration exposition families (lint:
#: migration-metric-pins). Every key is a ``serve_fleet_``-namespaced
#: row in docs/observability.md; the histogram renders pooled tails as
#: ``serve_fleet_p{50,99}_migration_ms``.
MIGRATION_METRIC_KEYS = (
    "serve_fleet_direct_migrations_total",
    "serve_fleet_migration_bytes_total",
    "serve_fleet_migration_ms",
    "serve_fleet_migration_link_cost_us",
)

_warned_bad_mode = False


def direct_migration_mode() -> str:
    """``"auto"`` or ``"off"`` from :data:`DIRECT_MIGRATION_ENV`.
    Lenient parse in the sane-env tradition: the off-ish spellings
    (``off``/``0``/``false``/``no``/``relayed``) disable, anything
    else (including unset) is ``auto`` — with a warn-once on garbage
    so a typo degrades loudly, not silently."""
    global _warned_bad_mode
    raw = os.environ.get(DIRECT_MIGRATION_ENV, "auto").strip().lower()
    if raw in ("off", "0", "false", "no", "relayed"):
        return "off"
    if raw not in ("auto", "on", "1", "true", "yes", "direct", ""):
        if not _warned_bad_mode:
            _warned_bad_mode = True
            warnings.warn(
                f"{DIRECT_MIGRATION_ENV}={raw!r} is not auto/off; "
                "treating as auto", stacklevel=2)
    return "auto"


def fleet_topology() -> Optional[Dict[str, Any]]:
    """The measured alpha-beta model for migration scoring, or
    ``None`` when no model exists. This is the ONE seam the router
    reads topology through — tests monkeypatch it with a synthetic
    model, and it swallows the not-initialized case (router fleets in
    tier-1 run without ``hvd.init()``; every topology export is
    controller-gated)."""
    try:
        from horovod_tpu import api
        return api.topology()
    except Exception:
        return None


def replica_rank(instance: str, n_ranks: int) -> int:
    """Map a router replica instance id onto a topology rank. Instance
    ids are the router's decimal join counter; fleets larger than the
    probed ring wrap (two replicas sharing a rank share its links,
    which is exactly the single-host reality)."""
    digits = "".join(c for c in instance if c.isdigit())
    return (int(digits) % n_ranks) if digits and n_ranks > 0 else 0


def link_cost_us(model: Optional[Dict[str, Any]], src: int, dst: int,
                 n_bytes: int) -> float:
    """One-shot alpha-beta cost of moving ``n_bytes`` src → dst under
    ``model`` (0 when loopback or no model). The single-span verdict —
    :func:`migration_cost_us` is the chunked generalization."""
    if model is None or src == dst:
        return 0.0
    alpha = model["alpha_us"][src][dst]
    beta = model["beta_us_per_byte"][src][dst]
    return alpha + beta * n_bytes


def migration_cost_us(model: Optional[Dict[str, Any]], src: int,
                      dst: int, n_bytes: int, n_chunks: int) -> float:
    """Cost verdict for streaming ``n_bytes`` src → dst in
    ``n_chunks`` pipelined chunks. Mirrored EXACTLY (same terms, same
    order) by the native ``hvd_migration_cost_us`` — change one,
    change both, and the sanitizer cross-check pins the agreement.

    Terms: every chunk pays launch + ack latency plus twice the span
    bookkeeping overhead; the full payload crosses the wire once; and
    the LAST chunk's inject cannot overlap anything, modeled as one
    chunk's worth of extra beta. More chunks buy overlap (smaller
    tail term) at the price of per-chunk latency — an interior
    minimum, which is the whole point of scheduling the transfer."""
    if n_chunks < 1:
        raise ValueError(f"n_chunks {n_chunks} < 1")
    if model is None or src == dst:
        return 0.0
    alpha_fwd = model["alpha_us"][src][dst]
    alpha_ack = model["alpha_us"][dst][src]
    beta = model["beta_us_per_byte"][src][dst]
    per_chunk = alpha_fwd + alpha_ack + 2.0 * SPAN_OVERHEAD_US
    return (n_chunks * per_chunk + n_bytes * beta
            + (n_bytes / n_chunks) * beta)


def codec_wire_ratio(codec) -> float:
    """Wire-bytes ratio of the span codec on an f32 pool: the cast
    codecs (bf16/fp16) halve every page, ``None`` ships raw. Accepts
    the same spellings ``rpc.span_codec_id`` does (name string,
    ``None``, or a ``hvd.Compression`` member)."""
    if codec is None:
        return 1.0
    wire = getattr(codec, "wire_codec", None)
    if wire is not None:
        return 0.5 if int(wire) in (1, 2) else 1.0
    return 0.5 if str(codec) in ("bf16", "fp16") else 1.0


def page_nbytes(model_cfg, block_size: int) -> int:
    """Raw bytes of one K+V page pair under ``model_cfg`` — the
    per-block unit the migration planner converts block counts into
    wire bytes with."""
    try:
        import numpy as _np
        itemsize = _np.dtype(model_cfg.dtype).itemsize
    except Exception:
        itemsize = 4
    return int(2 * model_cfg.n_layers * block_size
               * model_cfg.n_kv_heads * model_cfg.head_dim * itemsize)


def chunk_menu(n_pages: int) -> List[int]:
    """Candidate chunk sizes (in pages) the planner sweeps: powers of
    two up to the page count, plus the monolithic transfer."""
    if n_pages < 1:
        return [1]
    menu = []
    c = 1
    while c < n_pages:
        menu.append(c)
        c *= 2
    menu.append(n_pages)
    return menu


def plan_migration(n_pages: int, page_bytes: int, *,
                   src: int, dst: int,
                   codec: Optional[str] = None,
                   model: Optional[Dict[str, Any]] = None,
                   ) -> Dict[str, Any]:
    """Pick the chunk schedule for one candidate migration: sweep
    :func:`chunk_menu` through :func:`migration_cost_us` over the wire
    byte count (codec applied) and return the argmin::

        {"chunk_pages", "n_chunks", "cost_us", "wire_bytes"}

    No model (or loopback) → ONE monolithic chunk with cost 0: with
    no evidence that per-chunk latency is cheap, blind chunking only
    multiplies the target's per-chunk inject dispatches (measured to
    dominate small moves), so an unprobed fleet streams each sequence
    whole — exactly the relayed path's granularity — and placement
    degrades to pure least-load."""
    n_pages = max(int(n_pages), 1)
    wire_bytes = int(math.ceil(n_pages * page_bytes
                               * codec_wire_ratio(codec)))
    if model is None or src == dst:
        return {"chunk_pages": n_pages, "n_chunks": 1,
                "cost_us": 0.0, "wire_bytes": wire_bytes}
    best: Optional[Tuple[float, int, int]] = None
    for chunk in chunk_menu(n_pages):
        n_chunks = -(-n_pages // chunk)
        cost = migration_cost_us(model, src, dst, wire_bytes, n_chunks)
        if best is None or cost < best[0]:
            best = (cost, chunk, n_chunks)
    cost, chunk, n_chunks = best
    return {"chunk_pages": chunk, "n_chunks": n_chunks,
            "cost_us": cost, "wire_bytes": wire_bytes}
