"""Fleet trace merge: one Perfetto file, one timebase, one command.

The library behind ``bin/hvd-trace``. A fleet run scatters its
observability across files and clock epochs:

* ``router.json`` / ``replica-*.json`` — chrome traces from
  :meth:`ServeRouter.export_fleet_trace`, each with span ``ts`` in
  microseconds since that PROCESS's ``started_at`` on that process's
  ``perf_counter`` clock, plus a ``metadata`` anchor
  (``started_at`` / ``clock_now`` / ``wall_now`` / ``clock_offset``).
* ``flight-*.txt`` — native flight-recorder dumps (docs/
  observability.md "Flight recorder"): ``t_us`` on CLOCK_MONOTONIC
  (the Linux ``perf_counter`` epoch), with a ``mono_us``/``wall_us``
  header pair.
* ``timeline*.json`` — host timelines (``hvd.start_timeline``),
  B/E/i/C events with no anchor metadata (they ride along on their
  own timebase, each under its own pid, clearly labeled).

:func:`merge` maps everything anchored onto ONE timebase — the
router's wall clock, in microseconds — via each file's anchor pair
and the router's RTT-estimated per-worker ``clock_offset``
(rpc.py heartbeat midpoints). :func:`critical_path` then decomposes a
request's ``router:e2e`` span into an exact partition (queue wait /
rpc wire / prefill / handoff / decode / wait) whose rows sum to the
end-to-end latency BY CONSTRUCTION — it is an interval attribution
over the e2e window, not a sum of independently-measured pieces.
:func:`straggler_summary` ranks processes by collective barrier wait;
the rank everyone else waits on is the one that waits LEAST.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

#: Span-name priority for critical-path attribution, highest first:
#: where compute and a control phase overlap, the window is charged to
#: the compute (the control span covers it by definition). The last
#: resort, uncovered time, reports as "wait".
CRITICAL_PATH_PRIORITY = (
    "prefill", "decode", "spec", "handoff", "rpc_wire", "queue_wait",
)


# ---------------------------------------------------------------------------
# Loading
# ---------------------------------------------------------------------------

def _load_chrome_json(path: str) -> Optional[Dict[str, Any]]:
    """Parse a chrome-trace file: the object form (``traceEvents`` +
    ``metadata``) or the bare/unterminated array form the native
    timeline writer streams (trailing comma, no closing bracket — the
    format chrome://tracing itself tolerates)."""
    with open(path) as f:
        text = f.read()
    try:
        d = json.loads(text)
    except json.JSONDecodeError:
        # Streamed array: strip the trailing ",\n" and close it.
        t = text.strip()
        if t.startswith("["):
            t = t.rstrip().rstrip(",") + "]"
            try:
                d = json.loads(t)
            except json.JSONDecodeError:
                return None
        else:
            return None
    if isinstance(d, list):
        return {"traceEvents": d, "metadata": {}}
    if isinstance(d, dict) and "traceEvents" in d:
        d.setdefault("metadata", {})
        return d
    return None


_FLIGHT_HEADER = re.compile(
    r"^# flight v1 pid=(\d+) mono_us=(\d+) wall_us=(\d+)")


def _load_flight_dump(path: str) -> Optional[Dict[str, Any]]:
    """Parse a native flight dump into instant events on the dump
    process's WALL clock (the header's mono/wall pair maps each
    monotonic ``t_us`` over)."""
    with open(path) as f:
        lines = f.read().splitlines()
    if not lines:
        return None
    m = _FLIGHT_HEADER.match(lines[0])
    if m is None:
        return None
    pid, mono_us, wall_us = (int(g) for g in m.groups())
    events = []
    for line in lines[1:]:
        parts = line.split("\t")
        if len(parts) != 5:
            continue
        seq, t_us, name, a0, a1 = parts
        events.append({
            "name": f"flight:{name}", "ph": "i", "s": "t",
            "pid": pid, "tid": 0,
            # Already wall µs after the header mapping.
            "ts": wall_us + (int(t_us) - mono_us),
            "args": {"seq": int(seq), "a0": int(a0), "a1": int(a1)},
        })
    return {"kind": "flight", "pid": pid, "events": events}


def classify(path: str) -> str:
    """'serve' (anchored chrome trace), 'flight', 'timeline'
    (unanchored chrome trace), or 'skip'."""
    base = os.path.basename(path)
    if base.endswith(".txt"):
        return "flight" if base.startswith("flight") else "skip"
    if base.endswith(".json"):
        d = _load_chrome_json(path)
        if d is None:
            return "skip"
        return "serve" if d["metadata"].get("clock_now") else "timeline"
    return "skip"


def discover(target: str) -> List[str]:
    """Files to merge: ``target`` itself, or — for a directory —
    every ``*.json`` / ``flight-*.txt`` in it, sorted (router first so
    pid 0 stays the router)."""
    if os.path.isfile(target):
        return [target]
    out = []
    for name in sorted(os.listdir(target)):
        if name.endswith(".json") or (name.startswith("flight")
                                      and name.endswith(".txt")):
            out.append(os.path.join(target, name))
    # Router file leads: its anchor defines the merged timebase.
    out.sort(key=lambda p: (0 if os.path.basename(p) == "router.json"
                            else 1, p))
    return out


# ---------------------------------------------------------------------------
# Merging
# ---------------------------------------------------------------------------

def _wall_mapper(router_meta: Optional[Dict[str, Any]],
                 meta: Dict[str, Any]):
    """Returns f(ts_us) -> merged wall µs for one anchored file.

    ``ts_us`` is microseconds since ``meta['started_at']`` on the
    file's own clock. Own-clock absolute time re-anchors onto the
    ROUTER clock via ``clock_offset`` (own − router, 0 for the router
    itself), then onto wall time via the router's
    ``(clock_now, wall_now)`` pair — one pair, so every file lands on
    the SAME wall timebase even if their own wall clocks disagree.
    Without a router file, the file's own pair anchors it."""
    anchor = router_meta if router_meta is not None else meta
    offset = float(meta.get("clock_offset") or 0.0)
    started = float(meta["started_at"])
    c_now = float(anchor["clock_now"])
    w_now = float(anchor["wall_now"])

    def to_wall_us(ts_us: float) -> float:
        t_own = started + ts_us / 1e6          # own clock, seconds
        t_router = t_own - offset              # router clock
        return (w_now + (t_router - c_now)) * 1e6

    return to_wall_us


def merge(paths: List[str]) -> Dict[str, Any]:
    """Merge trace files onto one timebase. Returns a chrome-trace
    dict: every anchored event's ``ts`` is microseconds on the merged
    (router-wall) timebase, normalized so the earliest event is 0;
    each source file gets its own ``pid`` with a ``process_name``
    metadata event naming it. Unanchored timelines ride along under
    their own pid on their OWN timebase (flagged in the name — merging
    can't invent an anchor that was never recorded)."""
    router_meta = None
    loaded: List[Tuple[str, str, Dict[str, Any]]] = []
    for p in paths:
        kind = classify(p)
        if kind == "skip":
            continue
        if kind == "flight":
            d = _load_flight_dump(p)
            if d is not None:
                loaded.append((p, kind, d))
            continue
        d = _load_chrome_json(p)
        if d is None:
            continue
        if kind == "serve" and d["metadata"].get("kind") == "router" \
                and router_meta is None:
            router_meta = d["metadata"]
        loaded.append((p, kind, d))

    out_events: List[dict] = []
    sources: List[Dict[str, Any]] = []
    # Router wall anchor in µs: flight dumps are already wall µs on
    # their own wall clock; with a router anchor present they line up
    # directly (wall clocks of one host agree to NTP slop, and the
    # flight pair was taken in the dump process itself).
    next_pid = 0
    for path, kind, d in loaded:
        pid = next_pid
        next_pid += 1
        base = os.path.basename(path)
        if kind == "flight":
            label = f"flight {d['pid']} ({base})"
            events = d["events"]
            for e in events:
                e = dict(e)
                e["pid"] = pid
                out_events.append(e)
        elif kind == "serve":
            meta = d["metadata"]
            mk = meta.get("kind", "engine")
            inst = meta.get("instance")
            label = (f"router ({base})" if mk == "router"
                     else f"replica {inst} ({base})")
            to_wall = _wall_mapper(router_meta, meta)
            for e in d["traceEvents"]:
                e = dict(e)
                e["ts"] = to_wall(float(e.get("ts", 0.0)))
                e["pid"] = pid
                out_events.append(e)
        else:
            label = f"timeline ({base}) [unanchored timebase]"
            for e in d["traceEvents"]:
                if e.get("ph") == "M":
                    continue
                e = dict(e)
                e["pid"] = pid
                out_events.append(e)
        sources.append({"pid": pid, "path": path, "kind": kind,
                        "name": label})

    # Normalize: earliest ANCHORED event becomes ts 0 (µs stay µs).
    anchored_pids = {s["pid"] for s in sources
                     if s["kind"] in ("serve", "flight")}
    anchored_ts = [e["ts"] for e in out_events
                   if e["pid"] in anchored_pids]
    t0 = min(anchored_ts) if anchored_ts else 0.0
    for e in out_events:
        if e["pid"] in anchored_pids:
            e["ts"] = round(e["ts"] - t0, 1)
    meta_events = [
        {"name": "process_name", "ph": "M", "pid": s["pid"],
         "args": {"name": s["name"]}}
        for s in sources]
    return {
        "traceEvents": meta_events + out_events,
        "displayTimeUnit": "ms",
        "metadata": {
            "merged_from": [s["path"] for s in sources],
            "timebase": ("router wall clock, µs"
                         if router_meta is not None
                         else "per-file wall clock, µs"),
            "t0_wall_us": t0,
        },
    }


# ---------------------------------------------------------------------------
# Critical path
# ---------------------------------------------------------------------------

def _category(e: dict) -> Optional[str]:
    name = e.get("name", "")
    if name == "router:queue_wait":
        return "queue_wait"
    if name == "rpc:submit":
        return "rpc_wire"
    if name == "serve:prefill":
        return "prefill"
    if name == "router:handoff":
        return "handoff"
    if name == "serve:decode":
        return "decode"
    if name in ("serve:spec_draft", "serve:spec_verify"):
        return "spec"
    return None


def _carries(e: dict, tid: int) -> bool:
    args = e.get("args") or {}
    if args.get("trace") == tid:
        return True
    return tid in (args.get("traces") or ())


def trace_ids(events: List[dict]) -> List[int]:
    """Trace ids with a completed ``router:e2e`` span, in end order."""
    out = []
    for e in events:
        if e.get("name") == "router:e2e":
            tid = (e.get("args") or {}).get("trace")
            if tid:
                out.append(tid)
    return out


def critical_path(events: List[dict], tid: int) -> Dict[str, Any]:
    """Exact decomposition of trace ``tid``'s end-to-end window.

    Collects every span carrying ``tid`` (or, for batched decode /
    spec spans, listing it), clips to the ``router:e2e`` window, and
    attributes each instant of the window to the highest-priority
    covering category (:data:`CRITICAL_PATH_PRIORITY`); uncovered time
    is ``wait``. Because this partitions the window, the per-category
    microseconds sum EXACTLY to the e2e duration."""
    e2e = None
    for e in events:
        if e.get("name") == "router:e2e" and _carries(e, tid):
            e2e = e
            break
    if e2e is None:
        raise KeyError(f"no router:e2e span for trace {tid:#x}")
    w0 = float(e2e["ts"])
    w1 = w0 + float(e2e.get("dur", 0.0))

    spans: List[Tuple[float, float, str]] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        cat = _category(e)
        if cat is None or not _carries(e, tid):
            continue
        s0 = max(w0, float(e["ts"]))
        s1 = min(w1, float(e["ts"]) + float(e.get("dur", 0.0)))
        if s1 > s0:
            spans.append((s0, s1, cat))

    # Sweep the window's segment boundaries; charge each segment to
    # its best-priority covering span.
    cuts = sorted({w0, w1, *(s for s0, s1, _ in spans
                             for s in (s0, s1))})
    totals = {cat: 0.0 for cat in CRITICAL_PATH_PRIORITY}
    totals["wait"] = 0.0
    rank = {c: i for i, c in enumerate(CRITICAL_PATH_PRIORITY)}
    for a, b in zip(cuts, cuts[1:]):
        covering = [cat for s0, s1, cat in spans if s0 <= a and b <= s1]
        if covering:
            cat = min(covering, key=lambda c: rank[c])
        else:
            cat = "wait"
        totals[cat] += b - a
    return {
        "trace": tid,
        "rid": (e2e.get("args") or {}).get("rid"),
        "e2e_us": round(w1 - w0, 1),
        "breakdown_us": {k: round(v, 1) for k, v in totals.items()},
    }


# ---------------------------------------------------------------------------
# Straggler attribution
# ---------------------------------------------------------------------------

def straggler_summary(events: List[dict]) -> List[Dict[str, Any]]:
    """Per-pid collective barrier wait, ascending. The straggler is
    the process that waits LEAST at the barrier — everyone else's
    ``shm_barrier``/NEGOTIATE time is spent waiting for it. Sums 'X'
    span durations and B/E pairs whose name carries the barrier or
    negotiate markers; pids with none are omitted."""
    per: Dict[int, float] = {}
    open_b: Dict[Tuple[int, str, str], float] = {}
    for e in events:
        name = str(e.get("name", ""))
        barrier = ("barrier" in name.lower()
                   or name.startswith("NEGOTIATE"))
        pid = int(e.get("pid", 0))
        ph = e.get("ph")
        if ph == "X" and barrier:
            per[pid] = per.get(pid, 0.0) + float(e.get("dur", 0.0))
        elif ph == "B" and barrier:
            open_b[(pid, str(e.get("tid", "")), name)] = float(e["ts"])
        elif ph == "E":
            # The writer emits E with an empty name; close the newest
            # open barrier span on this (pid, tid).
            for key in sorted(open_b,
                              key=lambda k: -open_b[k]):
                if key[0] == pid and key[1] == str(e.get("tid", "")):
                    per[pid] = per.get(pid, 0.0) + \
                        (float(e["ts"]) - open_b.pop(key))
                    break
    return sorted(
        ({"pid": pid, "barrier_wait_us": round(us, 1)}
         for pid, us in per.items()),
        key=lambda r: r["barrier_wait_us"])
