"""Elastic training — worker side.

Rebuild of ``horovod/common/elastic.py:26-175``: a ``State`` object
carries everything training needs to survive a membership change
(commit/restore/sync), and the ``run`` wrapper turns collective
failures and host updates into state rollback + re-rendezvous instead
of job death.

Protocol differences from the reference are transport-level only: a
background watcher thread polls the launcher's KV store for the
membership epoch (the analog of the reference's push notification RPC,
``runner/elastic/worker.py``) so ``commit()`` /
``check_host_updates()`` see pending updates WITHOUT paying a KV
round-trip per call — updates are still *applied* only at those
boundaries, exactly like the reference. Re-rendezvous asks the elastic
driver's KV table for this worker's new coordinates instead of the
Gloo ``HOROVOD_GLOO_GET_RANK_AND_SIZE`` scope
(``gloo_context.cc:154-200``). Watcher cadence:
``HOROVOD_ELASTIC_POLL_SECS`` (default 1 s) bounds how stale a long
step window's view of membership can be.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

import cloudpickle

import horovod_tpu.api as api
from horovod_tpu.common.exceptions import (
    HorovodInternalError, HostsUpdatedInterrupt, WorkerExcludedError,
)
from horovod_tpu.common.topology import Topology
from horovod_tpu.functions import broadcast_object

ASSIGN_SCOPE = "elastic"

# KV-unreachable fallbacks (docs/observability.md
# elastic_kv_fallbacks_total): every failed watcher poll and every
# stale-forced direct read at a check boundary ticks this counter, so
# an outage the (re-armed) log warning only reports once is still
# visible — and sized — on a scrape. Python-side because the driver KV
# is a Python-plane dependency; exported as a fragment next to the
# native registry.
_kv_fallback_lock = threading.Lock()
_kv_fallbacks = 0


def _count_kv_fallback() -> None:
    global _kv_fallbacks
    with _kv_fallback_lock:
        _kv_fallbacks += 1


def kv_fallbacks_total() -> int:
    """Cumulative count of launcher-KV-unreachable fallbacks (failed
    watcher polls + stale-forced direct epoch reads)."""
    with _kv_fallback_lock:
        return _kv_fallbacks


def _render_kv_fallbacks() -> str:
    n = kv_fallbacks_total()
    name = "hvd_elastic_kv_fallbacks_total"
    return f"# TYPE {name} counter\n{name} {n}\n"


def _membership_external_epoch() -> int:
    """The driver-epoch component of the native membership plane
    (``hvd.membership().external_epoch``)."""
    from horovod_tpu.common.basics import get_lib
    return int(get_lib().hvd_membership_epoch()) >> 20


def _publish_membership_epoch(epoch: int) -> None:
    """Forward-only convergence of the KV-published driver epoch into
    the native membership plane: the watcher and ``hvd.membership()``
    report one number. Reset is only issued when the external component
    actually advances — re-publishing the current epoch would burn a
    generation via the plane's monotone clamp."""
    from horovod_tpu.common.basics import get_lib
    lib = get_lib()
    if epoch > (int(lib.hvd_membership_epoch()) >> 20):
        lib.hvd_membership_reset(epoch, lib.hvd_membership_size())


def _rdv() -> Optional[str]:
    return os.environ.get("HOROVOD_RENDEZVOUS_ADDR")


def _kv():
    from horovod_tpu.runner import http_kv
    return http_kv


def current_epoch() -> int:
    """The driver-published membership epoch (0 when not elastic)."""
    rdv = _rdv()
    if not rdv:
        return 0
    raw = _kv().kv_get(rdv, ASSIGN_SCOPE, "epoch")
    return int(raw) if raw else 0


class _EpochWatcher:
    """Daemon thread mirroring the driver-published epoch into this
    process (the notification-RPC analog): ``latest()`` is a memory
    read, so ``commit()`` costs no HTTP round-trip and a worker in a
    long step window is at most one poll interval stale. When polls
    keep FAILING, ``stale()`` turns true and the check boundaries fall
    back to a direct (loud-failing) KV read — a dead launcher store
    must not leave workers silently training on stale membership."""

    def __init__(self, initial_epoch: int):
        import time
        self._lock = threading.Lock()
        self._latest = initial_epoch
        try:
            iv = float(os.environ.get("HOROVOD_ELASTIC_POLL_SECS", "1.0"))
        except ValueError:
            iv = 1.0
        # Lower bound: 0 would busy-spin HTTP GETs at the KV server.
        self._interval = max(0.05, iv)
        self._last_ok = time.monotonic()
        self._stop = threading.Event()
        # The fallback counter is scrape-visible next to the native
        # registry the moment a watcher exists.
        from horovod_tpu.metrics import register_exporter
        register_exporter("elastic_kv", _render_kv_fallbacks)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="hvd-epoch-watcher")
        self._thread.start()

    def _run(self):
        import logging
        import time
        log = logging.getLogger("horovod_tpu")
        warned = False
        while not self._stop.wait(self._interval):
            try:
                e = current_epoch()
            except Exception:
                _count_kv_fallback()
                if not warned and self.stale():
                    warned = True
                    log.warning(
                        "elastic epoch watcher: launcher KV unreachable; "
                        "membership checks fall back to direct reads "
                        "(elastic_kv_fallbacks_total=%d)",
                        kv_fallbacks_total())
                continue
            if warned:
                # Re-arm: log the recovery so the outage has a visible
                # end, and let the NEXT outage warn again instead of
                # staying silent for the life of the process.
                warned = False
                log.info(
                    "elastic epoch watcher: launcher KV reachable again; "
                    "mirrored epoch reads resumed")
            self._last_ok = time.monotonic()
            self.observe(e)

    def observe(self, epoch: int) -> None:
        """Advance the mirrored epoch (forward-only) and converge it
        into the native membership plane, so ``hvd.membership()``'s
        external component and the watcher report one number."""
        with self._lock:
            if epoch > self._latest:
                self._latest = epoch
            latest = self._latest
        _publish_membership_epoch(latest)

    def latest(self) -> int:
        """Newest driver epoch this process has seen — the mirrored KV
        value or the membership plane's external component, whichever
        is ahead (re-init via HOROVOD_ELASTIC_EPOCH lands in the plane
        first)."""
        with self._lock:
            mine = self._latest
        return max(mine, _membership_external_epoch())

    def stale(self) -> bool:
        """True when polling has failed for several intervals — the
        mirror can no longer be trusted."""
        import time
        return time.monotonic() - self._last_ok > 5 * self._interval

    def stop(self):
        self._stop.set()


_watcher: Optional[_EpochWatcher] = None


def _epoch_watcher(initial_epoch: int = 0) -> Optional[_EpochWatcher]:
    """Process-wide watcher, started lazily on first State creation
    in an elastic job (None outside one)."""
    global _watcher
    if _watcher is None and _rdv():
        _watcher = _EpochWatcher(initial_epoch)
    return _watcher


class State:
    """Base state: commit/restore/sync + host-update detection
    (reference ``common/elastic.py:26-96``)."""

    def __init__(self, **kwargs):
        self._reset_callbacks = []
        self._known_epoch = current_epoch()
        # Seed (or advance) the watcher with the epoch just read — no
        # second KV round-trip, and the mirror never runs backwards.
        w = _epoch_watcher(self._known_epoch)
        if w is not None:
            w.observe(self._known_epoch)

    def register_reset_callbacks(self, callbacks) -> None:
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        for cb in self._reset_callbacks:
            cb()

    def commit(self) -> None:
        """Save a restore point, then surface any pending membership
        change as :class:`HostsUpdatedInterrupt`."""
        self.save()
        self.check_host_updates()

    def check_host_updates(self) -> None:
        w = _epoch_watcher()
        if w is not None and not w.stale():
            epoch = w.latest()
        else:
            # No watcher, or its polls keep failing: read directly so
            # a dead KV store fails LOUDLY at the check boundary
            # instead of silently freezing membership. A stale-forced
            # direct read is a fallback event — count it.
            if w is not None:
                _count_kv_fallback()
            epoch = current_epoch()
            if w is not None:
                w.observe(epoch)
        if epoch > self._known_epoch:
            self._known_epoch = epoch
            raise HostsUpdatedInterrupt()

    # subclass surface ---------------------------------------------------
    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError


class ObjectState(State):
    """State carrying arbitrary picklable attributes, synced from rank 0
    (reference ``ObjectState``, ``common/elastic.py:99-148``)."""

    def __init__(self, **kwargs):
        super().__init__()
        self._saved: Dict[str, Any] = dict(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self.save()  # deep-copied restore point, not aliased live attrs

    def _attrs(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._saved}

    def save(self) -> None:
        self._saved = {
            k: cloudpickle.loads(cloudpickle.dumps(v))
            for k, v in self._attrs().items()}

    def restore(self) -> None:
        for k, v in self._saved.items():
            setattr(self, k, cloudpickle.loads(cloudpickle.dumps(v)))

    def sync(self) -> None:
        synced = broadcast_object(self._attrs(), root_rank=0,
                                  name="elastic.object_state")
        for k, v in synced.items():
            setattr(self, k, v)
        self.save()


def _rendezvous_new_topology(timeout: float,
                             min_epoch: int = 0) -> Topology:
    """Ask the driver's KV table for this worker's coordinates (and the
    epoch's controller address) at the newest epoch. Raises
    WorkerExcludedError when this worker is not in the new assignment
    (its slot was removed).

    ``min_epoch``: after a collective FAILURE the driver is about to
    roll the epoch (it sees the dead process slightly later than the
    survivors see the broken connection); re-initializing at the old
    epoch would bind the old address while respawned workers dial the
    new one. Wait for the roll — bounded, because a global transient
    error (stall shutdown) never rolls and same-epoch re-init is then
    correct for everyone.
    """
    rdv = _rdv()
    identity = os.environ.get("HOROVOD_ELASTIC_ID")
    if not rdv or not identity:
        raise HorovodInternalError(
            "elastic reset requires a horovodrun elastic launch "
            "(HOROVOD_RENDEZVOUS_ADDR + HOROVOD_ELASTIC_ID)")
    kv = _kv()
    epoch = current_epoch()
    if epoch < min_epoch:
        import time
        deadline = time.monotonic() + min(timeout, 30.0)
        while epoch < min_epoch and time.monotonic() < deadline:
            time.sleep(0.1)
            epoch = current_epoch()
    payload = cloudpickle.loads(
        kv.kv_wait(rdv, ASSIGN_SCOPE, f"assign.{epoch}", timeout))
    slot = payload["slots"].get(identity)
    if slot is None:
        raise WorkerExcludedError(
            f"worker {identity} is not part of epoch {epoch}")
    # The driver picked the epoch's controller endpoint; rank 0 binds
    # every interface, others dial the published host.
    host, port = payload["controller_addr"].rsplit(":", 1)
    os.environ["HOROVOD_CONTROLLER_ADDR"] = (
        f"0.0.0.0:{port}" if slot.rank == 0 else f"{host}:{port}")
    # The epoch's rank 0 may be a different worker than at spawn time;
    # its advertised host (used for the jax.distributed coordinator
    # under --xla-exec) must be the driver-chosen routable one.
    os.environ["HOROVOD_CONTROLLER_HOST"] = host
    os.environ["HOROVOD_ELASTIC_EPOCH"] = str(epoch)
    return Topology(rank=slot.rank, size=slot.size,
                    local_rank=slot.local_rank, local_size=slot.local_size,
                    cross_rank=slot.cross_rank, cross_size=slot.cross_size)


def _init_with_retry(min_epoch: int = 0) -> None:
    """Rendezvous at the newest driver epoch and init, retrying
    in-process when an attempt fails.

    Membership can churn again while a process is between worlds (a
    second failure, a grow and a kill landing together): the address it
    rendezvoused against is then already dead, and a single-shot init
    would hang its full connect timeout there, exit nonzero, and record
    a host flap in the decay blacklist for what is really rendezvous
    churn — enough cascading casualties and the blacklist excludes a
    perfectly healthy host and starves the job. Re-reading the
    assignment table per attempt makes (re-)joining follow the
    membership plane instead of racing it; a worker that still cannot
    join after the retry budget dies nonzero, and THAT flap is
    deserved.

    Each attempt's native connect wait is bounded to a slice of the
    start timeout (an explicit ``HOROVOD_CONTROLLER_TIMEOUT_MS`` wins)
    so a roll mid-connect costs one slice, not the whole budget.
    """
    timeout = float(os.environ.get("HOROVOD_START_TIMEOUT", "120"))
    attempts = max(1, int(os.environ.get(
        "HOROVOD_ELASTIC_INIT_ATTEMPTS", "3")))
    pinned_ms = os.environ.get("HOROVOD_CONTROLLER_TIMEOUT_MS")
    attempt_ms = pinned_ms or str(int(
        max(15.0, timeout / attempts) * 1000))
    last_err: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            # WorkerExcludedError passes through: clean exit 0, our
            # slot shrank away while we were between worlds.
            topo = _rendezvous_new_topology(timeout, min_epoch)
        except Exception:
            if last_err is not None:
                raise last_err
            raise
        os.environ["HOROVOD_CONTROLLER_TIMEOUT_MS"] = attempt_ms
        try:
            api.init(topo)
            return
        except HorovodInternalError as e:
            last_err = e
            try:
                api.shutdown()
            except Exception:
                pass
            # The failed attempt usually means the epoch rolled under
            # us; ask the next rendezvous to wait (bounded, inside
            # _rendezvous_new_topology) for a NEWER epoch so it reads
            # the fresh table instead of re-dialing the same dead
            # address. A transient same-epoch failure falls through
            # after the bounded wait — same-epoch re-init is then
            # correct for every process.
            min_epoch = max(min_epoch, current_epoch() + 1)
        finally:
            if pinned_ms is None:
                os.environ.pop("HOROVOD_CONTROLLER_TIMEOUT_MS", None)
            else:
                os.environ["HOROVOD_CONTROLLER_TIMEOUT_MS"] = pinned_ms
            os.environ.pop("HOROVOD_CONTROLLER_ADDR", None)
    raise last_err


def _reset(min_epoch: int = 0) -> None:
    """Shutdown + re-rendezvous with the new membership (reference
    ``common/elastic.py`` ``reset()``: shutdown, re-init)."""
    api.shutdown()
    _init_with_retry(min_epoch)


def initial_init(runtime) -> None:
    """First init of a driver-spawned elastic worker: the spawn env
    pins the epoch the driver saw when it forked this process, which
    may be stale by the time the interpreter is up — rendezvous at the
    newest epoch instead, with the same bounded retry the in-process
    reset path uses (``runtime.init`` re-enters with an explicit
    topology, so this never recurses)."""
    del runtime  # the singleton api.init path is the re-entry point
    _init_with_retry()


def run(func: Callable) -> Callable:
    """``@hvd.elastic.run`` — wrap a training function taking a
    :class:`State` first argument (reference ``common/elastic.py:
    151-175``). On collective failure: restore last commit, re-init,
    retry. On host update: re-init (state is current), retry.
    """

    def wrapper(state: State, *args, **kwargs):
        reset_limit = int(os.environ.get("HOROVOD_ELASTIC_RESET_LIMIT", "0"))
        resets = 0
        while True:
            try:
                # sync itself is collective — a failure there recovers
                # the same way as one inside the training function.
                state.sync()
                return func(state, *args, **kwargs)
            except HorovodInternalError:
                state.restore()
                # A failure means membership is about to change; wait
                # for the driver's epoch roll before re-rendezvousing.
                min_epoch = state._known_epoch + 1
            except HostsUpdatedInterrupt:
                # check_host_updates already advanced _known_epoch to
                # the new epoch; rendezvous there.
                min_epoch = state._known_epoch
            resets += 1
            if reset_limit and resets >= reset_limit:
                raise RuntimeError(
                    f"elastic reset limit ({reset_limit}) reached")
            state.on_reset()
            _reset(min_epoch)
            state._known_epoch = current_epoch()

    return wrapper
