"""Public eager named-tensor API.

Mirrors the product surface of ``horovod.torch.mpi_ops`` /
``horovod.common.basics`` (reference ``torch/mpi_ops.py:95-882``,
``common/basics.py:33-288``): ``init``/``rank``/``size``, sync and
async (`*_async` + ``synchronize``/``poll``) variants of every
collective, Join, and barrier — with the data plane re-targeted to TPU
(XLA programs for device tensors, native TCP for host tensors).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from horovod_tpu.common import basics
from horovod_tpu.common.ops_enum import Average, ReduceOp, Sum
from horovod_tpu.common.topology import Topology
from horovod_tpu.runtime import Handle, get_runtime

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "allreduce", "allreduce_async",
    "grouped_allreduce", "grouped_allreduce_async", "allgather",
    "allgather_async", "broadcast", "broadcast_async", "alltoall",
    "alltoall_async", "reducescatter", "reducescatter_async", "join",
    "barrier", "synchronize", "poll", "mpi_threads_supported",
    "start_timeline", "stop_timeline", "reduce_threads",
    "set_reduce_threads", "metrics", "metrics_prometheus",
    "metrics_aggregate", "metrics_reset", "stalled_tensors",
    "start_metrics_server", "collective_algo", "topology",
    "topology_probe", "steady_lock_engaged", "steady_persistent",
    "membership",
]


def init(topology: Optional[Topology] = None) -> None:
    """Initialize the runtime (reference ``hvd.init()``,
    ``operations.cc:710``). Topology comes from launcher env vars when
    not given explicitly."""
    get_runtime().init(topology)


def shutdown() -> None:
    get_runtime().shutdown()


def is_initialized() -> bool:
    return get_runtime().initialized()


def rank() -> int:
    return get_runtime().rank()


def size() -> int:
    return get_runtime().size()


def local_rank() -> int:
    return get_runtime().local_rank()


def local_size() -> int:
    return get_runtime().local_size()


def cross_rank() -> int:
    return get_runtime().cross_rank()


def cross_size() -> int:
    return get_runtime().cross_size()


def mpi_threads_supported() -> bool:
    # No MPI underneath; the native controller is always thread-safe.
    return True


def reduce_threads() -> int:
    """Current host data-plane reduction thread budget (see
    ``docs/perf_tuning.md``; set via ``HOROVOD_REDUCE_THREADS`` or the
    autotuner)."""
    return get_runtime().reduce_threads()


def set_reduce_threads(n: int) -> None:
    """Override this process's host-reduction thread budget at runtime
    (bitwise-safe at any value; clamped to [1, 64])."""
    get_runtime().set_reduce_threads(n)


# ---------------------------------------------------------------------------
# telemetry (docs/observability.md)
# ---------------------------------------------------------------------------

def metrics():
    """Flat dict of the native registry's counters, gauges, and
    per-histogram count/sum/avg/p50/p99 — the continuously queryable
    counterpart of the chrome timeline. Works before ``init()`` (zeros)
    and needs no collective."""
    from horovod_tpu.metrics import metrics as _metrics_fn
    return _metrics_fn()


def metrics_prometheus() -> str:
    """Prometheus text exposition of the whole process: native runtime
    series plus any registered secondary exporter (the serving engine's
    ``ServeMetrics``). Serve it with :func:`start_metrics_server`."""
    from horovod_tpu.metrics import metrics_prometheus as _fn
    return _fn()


def metrics_aggregate():
    """Cross-rank ``{series: {"min", "max", "sum"}}`` reduced over the
    allreduce data plane. A COLLECTIVE — every rank must call it; the
    min/max spread of the timing series is the straggler signal."""
    from horovod_tpu.metrics import metrics_aggregate as _fn
    return _fn()


def metrics_reset() -> None:
    """Zero every native counter/histogram (scopes a measurement
    window, e.g. around a benchmark run)."""
    from horovod_tpu.metrics import metrics_reset as _fn
    _fn()


def stalled_tensors():
    """Coordinator-side stall findings as data (one ``{"name",
    "age_secs", "missing_ranks"}`` per tensor past the warning age) —
    the queryable form of the StallInspector's log warning."""
    from horovod_tpu.metrics import stalled_tensors as _fn
    return _fn()


def flight_events():
    """Decoded snapshot of the native flight recorder — the always-on
    control-plane event ring (lock engage/release, membership epochs,
    negotiation cycle summaries, stall findings, peer deaths, autotune
    verdicts). One ``{"seq", "t_us", "event", "a0", "a1"}`` dict per
    surviving slot, oldest first; ``t_us`` is CLOCK_MONOTONIC
    microseconds. See ``docs/observability.md`` for the event catalog."""
    from horovod_tpu.metrics import flight_events as _fn
    return _fn()


def flight_record(event: int, a0: int = 0, a1: int = 0) -> None:
    """Append one event to the native flight ring from Python (the
    serve plane records requeues and router-side findings this way).
    ``event`` is a ``FLIGHT_*`` id from :mod:`horovod_tpu.common.basics`."""
    from horovod_tpu.metrics import flight_record as _fn
    _fn(event, a0, a1)


def flight_dump(path=None) -> bool:
    """Write the flight ring to ``path`` (or, when None, to the
    auto-dump path under ``HOROVOD_FLIGHT_DIR``). Returns True on
    success. The same dump fires automatically on fatal signals and
    :class:`~horovod_tpu.common.exceptions.HorovodInternalError` when
    ``HOROVOD_FLIGHT_DIR`` is set."""
    from horovod_tpu.metrics import flight_dump as _fn
    return _fn(path)


def flight_clear() -> None:
    """Drop every recorded flight event (scopes a test or measurement
    window, like :func:`metrics_reset` for the event ring)."""
    from horovod_tpu.metrics import flight_clear as _fn
    _fn()


def steady_lock_engaged() -> bool:
    """True while this rank runs the steady-state schedule lock's
    negotiation-bypass plane (``HOROVOD_STEADY_LOCK``, see
    ``docs/perf_tuning.md``). Also visible as the ``ctrl_locked``
    gauge in :func:`metrics`."""
    from horovod_tpu.common.basics import get_lib
    return bool(get_lib().hvd_steady_lock_engaged())


def steady_persistent() -> bool:
    """True when the persistent locked data plane is enabled — the
    coordinator-synced ``HOROVOD_STEADY_PERSISTENT`` verdict (see
    ``docs/perf_tuning.md`` "Persistent locked data plane"). Its live
    footprint shows as the ``tcp_prepost_buffers`` gauge in
    :func:`metrics`."""
    from horovod_tpu.common.basics import get_lib
    return get_lib().hvd_steady_persistent() == 0


def membership():
    """Snapshot of the process-global membership plane (ABI v12,
    ``docs/elastic.md``): the monotone epoch every stateful consumer
    fences on, plus the active rank set.

    Works before ``init()`` — the elastic driver's epoch publisher and
    the serving router's replica plane ride the same accessor from
    processes that never initialize the collective core. Returns a
    namedtuple ``(epoch, generation, external_epoch, size, ranks)``
    where ``epoch == external_epoch << 20 | generation``: the external
    component is the driver-published ``HOROVOD_ELASTIC_EPOCH``, the
    generation counts in-job changes (Join flushes, dead peers,
    explicit shrinks)."""
    import ctypes
    from collections import namedtuple

    lib = basics.get_lib()
    n = lib.hvd_membership_ranks(None, 0)
    buf = (ctypes.c_int * max(n, 1))()
    lib.hvd_membership_ranks(buf, n)
    Membership = namedtuple(
        "Membership", ["epoch", "generation", "external_epoch", "size",
                       "ranks"])
    epoch = int(lib.hvd_membership_epoch())
    return Membership(
        epoch=epoch,
        generation=int(lib.hvd_membership_generation()),
        external_epoch=epoch >> 20,
        size=int(lib.hvd_membership_size()),
        ranks=tuple(buf[i] for i in range(n)),
    )


def start_metrics_server(port: int = 0, addr: str = "0.0.0.0"):
    """Serve the Prometheus exposition over HTTP (typically rank 0);
    returns the server — bound port at ``server.server_address[1]``."""
    from horovod_tpu.metrics import start_metrics_server as _fn
    return _fn(port, addr)


def _resolve_op(op: Optional[ReduceOp], average: Optional[bool]) -> ReduceOp:
    if op is not None and average is not None:
        raise ValueError("specify either op= or the legacy average=, not both")
    if op is None:
        op = Average if (average is None or average) else Sum
    return op


# ---------------------------------------------------------------------------
# allreduce
# ---------------------------------------------------------------------------

def allreduce_async(tensor, average: Optional[bool] = None,
                    name: Optional[str] = None, op: Optional[ReduceOp] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    compression=None, algorithm=None) -> Handle:
    """``compression`` (a ``hvd.Compression`` member) selects the
    native TCP data plane's on-the-wire codec for this op — e.g.
    ``hvd.Compression.int8`` ships blockwise-quantized bytes with
    error feedback while the user-visible tensor stays full precision.
    ``None`` follows the job-wide ``HOROVOD_WIRE_COMPRESSION`` knob.

    ``algorithm`` forces the TCP-plane exchange for this op: one of
    ``"ring"``, ``"hd"`` (recursive halving-doubling), ``"striped"``
    (multi-ring striping), ``"doubling"``, ``"hier"``. ``None`` follows
    the coordinator's per-(payload, np, topology) selection table (or
    the job-wide ``HOROVOD_COLLECTIVE_ALGO`` force). The coordinator
    resolves the final algorithm into each response, so every rank
    always runs the same exchange. See ``docs/perf_tuning.md``."""
    rt = get_runtime()
    return rt.enqueue(
        basics.OP_ALLREDUCE, tensor, rt.auto_name("allreduce", name),
        reduce_op=_resolve_op(op, average), prescale_factor=prescale_factor,
        postscale_factor=postscale_factor, compression=compression,
        algorithm=algorithm)


def allreduce(tensor, average: Optional[bool] = None,
              name: Optional[str] = None, op: Optional[ReduceOp] = None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              compression=None, algorithm=None):
    return synchronize(allreduce_async(tensor, average, name, op,
                                       prescale_factor, postscale_factor,
                                       compression, algorithm))


def grouped_allreduce_async(tensors: Sequence, average: Optional[bool] = None,
                            name: Optional[str] = None,
                            op: Optional[ReduceOp] = None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            compression=None,
                            algorithm=None) -> List[Handle]:
    """Atomic multi-tensor allreduce (reference
    ``EnqueueTensorAllreduces``, ``operations.cc:943`` + GroupTable).
    The member names are hashed into a rank-invariant group key.
    ``compression`` and ``algorithm`` ride every member (the
    coordinator only fuses matching settings, so the group stays one
    response)."""
    rt = get_runtime()
    reduce_op = _resolve_op(op, average)
    base = rt.auto_name("grouped_allreduce", name)
    names = [f"{base}.{i}" for i in range(len(tensors))]
    key = _group_key(names)
    return [
        rt.enqueue(basics.OP_ALLREDUCE, t, nm, reduce_op=reduce_op,
                   prescale_factor=prescale_factor,
                   postscale_factor=postscale_factor,
                   group_key=key, group_size=len(tensors),
                   compression=compression, algorithm=algorithm)
        for t, nm in zip(tensors, names)
    ]


def grouped_allreduce(tensors: Sequence, average: Optional[bool] = None,
                      name: Optional[str] = None,
                      op: Optional[ReduceOp] = None,
                      prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      compression=None, algorithm=None) -> List:
    handles = grouped_allreduce_async(tensors, average, name, op,
                                      prescale_factor, postscale_factor,
                                      compression, algorithm)
    return [synchronize(h) for h in handles]


def collective_algo() -> str:
    """The live job-wide collective-algorithm force for the TCP data
    plane, as a name (``"auto"`` = the per-(payload, np, topology)
    selection table decides per response). Reflects
    ``HOROVOD_COLLECTIVE_ALGO`` after the coordinator param sync plus
    any autotuner retarget."""
    lib = basics.get_lib()
    return lib.hvd_algo_name(lib.hvd_collective_algo()).decode()


def topology():
    """The measured alpha-beta link model driving schedule synthesis
    and measured algorithm selection (docs/perf_tuning.md "Measured
    topology & schedule synthesis"), or ``None`` when no model exists
    (``HOROVOD_TOPOLOGY_PROBE=off``, single-process jobs, or a failed
    probe). Every rank holds the identical broadcast numbers.

    Returns ``{"np": P, "alpha_us": [[...]], "beta_us_per_byte":
    [[...]]}`` with ``alpha_us[src][dst]`` the measured one-way launch
    latency and ``beta_us_per_byte[src][dst]`` the inverse bandwidth of
    the src→dst data link."""
    import ctypes

    lib = basics.get_lib()
    np_ = lib.hvd_topology(None, None, 0)
    if np_ <= 0:
        return None
    n2 = np_ * np_
    alpha = (ctypes.c_double * n2)()
    beta = (ctypes.c_double * n2)()
    lib.hvd_topology(alpha, beta, n2)
    return {
        "np": np_,
        "alpha_us": [[alpha[s * np_ + d] for d in range(np_)]
                     for s in range(np_)],
        "beta_us_per_byte": [[beta[s * np_ + d] for d in range(np_)]
                             for s in range(np_)],
    }


def topology_probe() -> float:
    """Re-run the pairwise link probe NOW and install the fresh model
    on every rank (rank 0 also rewrites the disk cache).

    COLLECTIVE CONTRACT: every rank must call this, with no collectives
    in flight — the probe's ping-pong rounds ride the same quiet data
    links the exchanges use. Returns the probe wall-clock in
    milliseconds; raises on failure (all ranks then agree there is no
    model and selection falls back to the hand-seeded bands)."""
    ms = float(basics.get_lib().hvd_topology_probe())
    if ms < 0:
        raise RuntimeError(
            "topology probe failed (single-process job, lost data link, "
            "or a rank measured garbage); selection falls back to the "
            "hand-seeded bands")
    return ms


def _group_key(names: Sequence[str]) -> int:
    # FNV-1a over the sorted member names — identical on every rank.
    h = 1469598103934665603
    for nm in sorted(names):
        for b in nm.encode():
            h = ((h ^ b) * 1099511628211) & 0x7FFFFFFFFFFFFFFF
    return h


# ---------------------------------------------------------------------------
# allgather / broadcast / alltoall / reducescatter
# ---------------------------------------------------------------------------

def allgather_async(tensor, name: Optional[str] = None) -> Handle:
    rt = get_runtime()
    return rt.enqueue(basics.OP_ALLGATHER, tensor,
                      rt.auto_name("allgather", name))


def allgather(tensor, name: Optional[str] = None):
    return synchronize(allgather_async(tensor, name))


def broadcast_async(tensor, root_rank: int = 0,
                    name: Optional[str] = None) -> Handle:
    rt = get_runtime()
    return rt.enqueue(basics.OP_BROADCAST, tensor,
                      rt.auto_name("broadcast", name), root_rank=root_rank)


def broadcast(tensor, root_rank: int = 0, name: Optional[str] = None):
    return synchronize(broadcast_async(tensor, root_rank, name))


def alltoall_async(tensor, splits=None, name: Optional[str] = None) -> Handle:
    rt = get_runtime()
    return rt.enqueue(basics.OP_ALLTOALL, tensor,
                      rt.auto_name("alltoall", name), splits=splits)


def alltoall(tensor, splits=None, name: Optional[str] = None):
    """Returns (tensor, received_splits) like the reference
    (``torch/mpi_ops.py`` alltoall returns recv splits when asked; we
    always return them — drop with ``[0]`` if unneeded)."""
    h = alltoall_async(tensor, splits, name)
    rt = get_runtime()
    out, st = rt.synchronize(h)
    return out, st.recvsplits


def reducescatter_async(tensor, op: Optional[ReduceOp] = None,
                        name: Optional[str] = None,
                        prescale_factor: float = 1.0,
                        postscale_factor: float = 1.0) -> Handle:
    if op == ReduceOp.ADASUM:
        raise ValueError("adasum reducescatter is not defined; use allreduce")
    rt = get_runtime()
    return rt.enqueue(basics.OP_REDUCESCATTER, tensor,
                      rt.auto_name("reducescatter", name),
                      reduce_op=op if op is not None else Average,
                      prescale_factor=prescale_factor,
                      postscale_factor=postscale_factor)


def reducescatter(tensor, op: Optional[ReduceOp] = None,
                  name: Optional[str] = None, prescale_factor: float = 1.0,
                  postscale_factor: float = 1.0):
    return synchronize(reducescatter_async(tensor, op, name, prescale_factor,
                                           postscale_factor))


# ---------------------------------------------------------------------------
# join / barrier / handles
# ---------------------------------------------------------------------------

def join() -> None:
    """Signal that this rank has no more data (reference ``hvd.join()``,
    ``EnqueueJoin`` operations.cc:1197): pending collectives from other
    ranks proceed with this rank contributing zeros; returns when every
    rank has joined."""
    rt = get_runtime()
    rt.synchronize(rt.join())


def barrier() -> None:
    rt = get_runtime()
    rt.synchronize(rt.barrier())


def synchronize(handle: Handle):
    """Block until an async handle completes; returns the output tensor
    (reference ``torch/mpi_ops.py`` ``synchronize``)."""
    out, _st = get_runtime().synchronize(handle)
    return out


def poll(handle: Handle) -> bool:
    return get_runtime().poll(handle)


def start_timeline(path: str) -> None:
    get_runtime().start_timeline(path)


def stop_timeline() -> None:
    get_runtime().stop_timeline()
