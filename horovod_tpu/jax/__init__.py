"""JAX binding: the first-class TPU framework surface.

``import horovod_tpu.jax as hvd`` mirrors what ``horovod.tensorflow``
is to TF (reference ``tensorflow/__init__.py:427-790``): the full
collective API plus

* :func:`distributed_optimizer` — an optax ``GradientTransformation``
  wrapper (the ``DistributedOptimizer`` analog),
* :func:`distributed_value_and_grad` / :func:`allreduce_gradients` —
  the ``DistributedGradientTape`` analog,
* :func:`broadcast_parameters` / :func:`broadcast_object` /
  :func:`allgather_object` — bootstrap + checkpoint helpers on
  pytrees.

Two execution tiers, chosen by ``axis_name``:

* ``axis_name=None`` (default): the **eager named-tensor runtime** —
  per-leaf grouped allreduce negotiated by the native core, matching
  Horovod's process-per-rank model.
* ``axis_name="dp"`` (or a tuple): **in-jit SPMD** — ``lax.psum`` /
  ``pmean`` inside your ``shard_map``/``pjit`` program, compiled onto
  ICI by XLA. This is the TPU-idiomatic fast path.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import horovod_tpu.api as api
from horovod_tpu.api import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, allreduce, allreduce_async, grouped_allreduce,
    grouped_allreduce_async, allgather, allgather_async, broadcast,
    broadcast_async, alltoall, alltoall_async, reducescatter,
    reducescatter_async, join, barrier, synchronize, poll,
    mpi_threads_supported, start_timeline, stop_timeline,
)
from horovod_tpu.common.exceptions import HorovodInternalError  # noqa: F401
from horovod_tpu.common.ops_enum import (  # noqa: F401
    Adasum, Average, Max, Min, Product, ReduceOp, Sum,
)
from horovod_tpu.compression import Compression  # noqa: F401
from horovod_tpu.functions import (  # noqa: F401
    allgather_object, broadcast_object,
)

AxisName = Union[str, tuple]


def allreduce_gradients(grads: Any, *, axis_name: Optional[AxisName] = None,
                        op: ReduceOp = Average,
                        compression=Compression.none,
                        name: str = "grads") -> Any:
    """Reduce a gradient pytree across ranks.

    In-jit (``axis_name`` given): per-leaf ``lax.psum``/``pmean`` —
    call inside ``shard_map``; XLA fuses and schedules the collectives.
    Only leaves that are actually device-varying over ``axis_name``
    (``jax.typeof(leaf).vma``) are reduced: under JAX's varying-manual-
    axes typing, autodiff cotangents of *replicated* parameters are
    already globally correct (the mean-vs-sum choice lives in the loss
    — see :func:`distributed_value_and_grad`), and an explicit psum on
    them would double-count.
    Eager (no ``axis_name``): one grouped allreduce over all leaves via
    the native-negotiated runtime, so fusion batches small gradients.
    """
    import jax

    leaves, treedef = jax.tree.flatten(grads)
    if axis_name is not None:
        from jax import lax
        axes = ({axis_name} if isinstance(axis_name, str)
                else set(axis_name))

        def reduce_leaf(g):
            vma = getattr(jax.typeof(g), "vma", frozenset())
            if not (axes & set(vma)):
                return g  # replicated or already-reduced cotangent
            # Compression casts around the collective (wire dtype); XLA
            # fuses the casts into the psum's own data movement.
            g, ctx = compression.compress(g)
            if op == Average:
                g = lax.pmean(g, axis_name)
            elif op == Sum:
                g = lax.psum(g, axis_name)
            elif op == Max:
                g = lax.pmax(g, axis_name)
            elif op == Min:
                g = lax.pmin(g, axis_name)
            elif op == Adasum:
                from horovod_tpu.ops.adasum import adasum_allreduce
                g = adasum_allreduce(g, axis_name)
            else:
                raise ValueError(
                    f"in-jit gradient reduction with op={op!r} is not "
                    "supported (use Average/Sum/Max/Min/Adasum)")
            return compression.decompress(g, ctx)

        return jax.tree.unflatten(treedef, [reduce_leaf(g) for g in leaves])

    compressed, ctxs = [], []
    for g in leaves:
        c, ctx = compression.compress(g)
        compressed.append(c)
        ctxs.append(ctx)
    reduced = api.grouped_allreduce(compressed, name=name, op=op)
    out = [compression.decompress(r, ctx) for r, ctx in zip(reduced, ctxs)]
    return jax.tree.unflatten(treedef, out)


def distributed_optimizer(optimizer, *,
                          axis_name: Optional[AxisName] = None,
                          op: ReduceOp = Average,
                          compression=Compression.none,
                          name: str = "distributed_optimizer"):
    """Wrap an optax ``GradientTransformation`` so incoming gradients
    are reduced across ranks before the inner update — the optax
    analog of ``hvd.DistributedOptimizer``.

    Use inside ``jit``/``shard_map`` with ``axis_name=...``, or eagerly
    (one process per rank) without.
    """
    import optax

    def init_fn(params):
        return optimizer.init(params)

    def update_fn(updates, state, params=None, **extra):
        updates = allreduce_gradients(
            updates, axis_name=axis_name, op=op, compression=compression,
            name=name)
        return optimizer.update(updates, state, params, **extra)

    return optax.GradientTransformation(init_fn, update_fn)


def distributed_value_and_grad(fun: Callable, argnums=0, *,
                               has_aux: bool = False,
                               axis_name: Optional[AxisName] = None,
                               op: ReduceOp = Average,
                               compression=Compression.none,
                               name: str = "distributed_grad") -> Callable:
    """``jax.value_and_grad`` whose gradients arrive pre-reduced across
    ranks — the ``DistributedGradientTape`` analog (reference
    ``tensorflow/__init__.py:723-790``).

    In-jit tier: the *loss itself* is reduced over ``axis_name``
    (``pmean`` for Average, ``psum`` for Sum) and autodiff then yields
    the exactly-corresponding global gradients — the VMA-correct way to
    express data-parallel training under ``shard_map`` (an explicit
    psum of replicated-param cotangents would double-count).
    Eager tier: local grads are computed, then group-allreduced.
    """
    import jax

    if axis_name is not None:
        from jax import lax
        if op not in (Average, Sum):
            raise ValueError(
                "in-jit distributed_value_and_grad supports Average/Sum")

        def global_fun(*args, **kwargs):
            out = fun(*args, **kwargs)
            if has_aux:
                loss, aux = out
            else:
                loss, aux = out, None
            loss = (lax.pmean(loss, axis_name) if op == Average
                    else lax.psum(loss, axis_name))
            return (loss, aux) if has_aux else loss

        return jax.value_and_grad(global_fun, argnums=argnums,
                                  has_aux=has_aux)

    vg = jax.value_and_grad(fun, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        value, grads = vg(*args, **kwargs)
        grads = allreduce_gradients(
            grads, axis_name=axis_name, op=op, compression=compression,
            name=name)
        return value, grads

    return wrapped


def broadcast_parameters(params: Any, root_rank: int = 0,
                         name: str = "broadcast_parameters") -> Any:
    """Broadcast a parameter pytree from ``root_rank``; returns the
    synced pytree (functional — jax arrays are immutable, unlike the
    reference's in-place ``torch/functions.py:29``)."""
    import jax

    leaves, treedef = jax.tree.flatten(params)
    handles = [api.broadcast_async(leaf, root_rank=root_rank,
                                   name=f"{name}.{i}")
               for i, leaf in enumerate(leaves)]
    synced = []
    for leaf, h in zip(leaves, handles):
        out = api.synchronize(h)
        synced.append(out.reshape(leaf.shape) if hasattr(out, "reshape")
                      else out)
    return jax.tree.unflatten(treedef, synced)


def sync_batch_norm(x, *, axis_name: AxisName = "dp",
                    scale=None, bias=None, eps: float = 1e-5,
                    reduce_dims=None):
    """Normalize ``x`` with batch statistics taken over BOTH the local
    reduce dims and the ``axis_name`` mesh axis — the in-jit SPMD analog
    of the reference's SyncBatchNorm (``torch/sync_batch_norm.py:22``,
    ``tensorflow/sync_batch_norm.py:22``). Call inside
    ``shard_map``/``pjit``; stats ride two small ``psum``\\ s that XLA
    fuses into one.

    ``reduce_dims`` defaults to all dims except the last (channel).
    Returns ``(y, mean, var)`` so callers can maintain running stats.
    For flax models, ``flax.linen.BatchNorm(axis_name="dp")`` achieves
    the same inside ``pjit`` — this helper is the framework-free form.
    """
    import jax.numpy as jnp
    from jax import lax

    if reduce_dims is None:
        reduce_dims = tuple(range(x.ndim - 1))
    reduce_dims = tuple(d % x.ndim for d in reduce_dims)
    h = x.astype(jnp.float32)
    n_local = 1
    for d in reduce_dims:
        n_local *= x.shape[d]
    stats = jnp.stack([jnp.sum(h, axis=reduce_dims),
                       jnp.sum(h * h, axis=reduce_dims)])
    stats = lax.psum(stats, axis_name)
    from horovod_tpu.ops.collectives import axis_size
    n = n_local * axis_size(axis_name)
    mean = stats[0] / n
    var = stats[1] / n - mean * mean
    # Broadcast stats back to x's layout: kept (channel) dims stay,
    # reduced dims become 1 — so NCHW-style reduce_dims=(0, 2, 3)
    # works, not just channels-last.
    bshape = [1 if d in reduce_dims else x.shape[d] for d in range(x.ndim)]
    y = (h - mean.reshape(bshape)) * lax.rsqrt(var.reshape(bshape) + eps)
    if scale is not None:
        y = y * scale.astype(jnp.float32).reshape(bshape)
    if bias is not None:
        y = y + bias.astype(jnp.float32).reshape(bshape)
    return y.astype(x.dtype), mean, var
