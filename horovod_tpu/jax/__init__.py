"""JAX binding: the first-class TPU framework surface.

``import horovod_tpu.jax as hvd`` mirrors what ``horovod.tensorflow``
is to TF (reference ``tensorflow/__init__.py:427-790``): the full
collective API plus

* :func:`distributed_optimizer` — an optax ``GradientTransformation``
  wrapper (the ``DistributedOptimizer`` analog),
* :func:`distributed_value_and_grad` / :func:`allreduce_gradients` —
  the ``DistributedGradientTape`` analog,
* :func:`broadcast_parameters` / :func:`broadcast_object` /
  :func:`allgather_object` — bootstrap + checkpoint helpers on
  pytrees.

Two execution tiers, chosen by ``axis_name``:

* ``axis_name=None`` (default): the **eager named-tensor runtime** —
  per-leaf grouped allreduce negotiated by the native core, matching
  Horovod's process-per-rank model.
* ``axis_name="dp"`` (or a tuple): **in-jit SPMD** — ``lax.psum`` /
  ``pmean`` inside your ``shard_map``/``pjit`` program, compiled onto
  ICI by XLA. This is the TPU-idiomatic fast path.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import horovod_tpu.api as api
from horovod_tpu.api import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, allreduce, allreduce_async, grouped_allreduce,
    grouped_allreduce_async, allgather, allgather_async, broadcast,
    broadcast_async, alltoall, alltoall_async, reducescatter,
    reducescatter_async, join, barrier, synchronize, poll,
    mpi_threads_supported, start_timeline, stop_timeline,
    metrics, metrics_prometheus, metrics_aggregate, metrics_reset,
    stalled_tensors, start_metrics_server,
)
from horovod_tpu.common.exceptions import HorovodInternalError  # noqa: F401
from horovod_tpu.common.ops_enum import (  # noqa: F401
    Adasum, Average, Max, Min, Product, ReduceOp, Sum,
)
from horovod_tpu.compression import Compression  # noqa: F401
from horovod_tpu.functions import (  # noqa: F401
    allgather_object, broadcast_object,
)

AxisName = Union[str, tuple]


def allreduce_gradients(grads: Any, *, axis_name: Optional[AxisName] = None,
                        op: ReduceOp = Average,
                        compression=Compression.none,
                        name: str = "grads", ef: Any = None) -> Any:
    """Reduce a gradient pytree across ranks.

    In-jit (``axis_name`` given): per-leaf ``lax.psum``/``pmean`` —
    call inside ``shard_map``; XLA fuses and schedules the collectives.
    Only leaves that are actually device-varying over ``axis_name``
    (``jax.typeof(leaf).vma``) are reduced: under JAX's varying-manual-
    axes typing, autodiff cotangents of *replicated* parameters are
    already globally correct (the mean-vs-sum choice lives in the loss
    — see :func:`distributed_value_and_grad`), and an explicit psum on
    them would double-count. With ``compression``, reduced leaves ride
    the quantized reduce-scatter + all-gather of
    :mod:`horovod_tpu.ops.quantized` (narrow bytes on both hops).
    Eager (no ``axis_name``): one grouped allreduce over all leaves via
    the native-negotiated runtime, so fusion batches small gradients;
    ``compression`` maps to the framework cast (bf16/fp16) or the
    native wire codec (int8) — the same knob either way.

    ``ef`` (in-jit int8 only): a pytree of rank-local error-feedback
    residuals matching ``grads`` (f32, zeros at step 0). When given,
    returns ``(reduced, new_ef)`` so callers — normally
    :func:`distributed_optimizer`, which threads it as optimizer-state
    leaves — carry this step's rounding error into the next. Without
    it, quantization error is dropped each step.
    """
    import jax

    from horovod_tpu import compression as compression_lib
    if compression is None:  # every surface reads None = uncompressed
        compression = Compression.none
    codec = compression_lib.in_jit_codec(compression)

    leaves, treedef = jax.tree.flatten(grads)
    if axis_name is not None:
        from jax import lax
        axes = ({axis_name} if isinstance(axis_name, str)
                else set(axis_name))

        def leaf_varies(g):
            # Legacy jax (no VMA types): every shard_map value is
            # implicitly varying, so always reduce. Keyed on the same
            # HAS_VMA flag as distributed_value_and_grad — the two
            # sites must agree or gradients silently go unreduced.
            from horovod_tpu.common import jax_compat
            vma = (getattr(jax.typeof(g), "vma", frozenset())
                   if jax_compat.HAS_VMA and hasattr(jax, "typeof")
                   else axes)
            return bool(axes & set(vma))

        if codec == "int8":
            # int8 has no cast form to fall back on: anything the
            # quantized path can't express is an error up front.
            if op not in (Average, Sum):
                raise ValueError(
                    f"in-jit compression=int8 supports op=Average/Sum "
                    f"only (there is no meaningful quantized {op!r}); "
                    "the cast codecs (bf16/fp16) still wrap "
                    "Max/Min/Adasum")
            if not isinstance(axis_name, str):
                raise NotImplementedError(
                    "in-jit compression=int8 reduces over a single "
                    f"named axis; got {axis_name!r} — reshape the mesh "
                    "or reduce axis-by-axis")
        if (codec != "none" and op in (Average, Sum)
                and isinstance(axis_name, str)):
            from horovod_tpu.ops.quantized import quantized_allreduce
            ef_leaves = (jax.tree.flatten(ef)[0] if ef is not None
                         else [None] * len(leaves))
            out, new_ef = [], []
            for g, r in zip(leaves, ef_leaves):
                if not leaf_varies(g):
                    out.append(g)
                    new_ef.append(r)
                    continue
                res = quantized_allreduce(g, op=op, axis_name=axis_name,
                                          codec=codec, residual=r)
                if r is None:
                    out.append(res)
                    new_ef.append(None)
                else:
                    out.append(res[0])
                    new_ef.append(res[1])
            reduced = jax.tree.unflatten(treedef, out)
            if ef is None:
                return reduced
            return reduced, jax.tree.unflatten(treedef, new_ef)

        def reduce_leaf(g):
            if not leaf_varies(g):
                return g  # replicated or already-reduced cotangent
            # Cast codecs wrap whatever the quantized branch doesn't
            # take (Max/Min/Adasum, and tuple-axis reductions) the
            # pre-PR way: cast to the wire dtype around the collective
            # (identity for Compression.none). Single-axis Average/Sum
            # with a codec never reach here — they ride the quantized
            # branch above.
            g, ctx = compression.compress(g)
            if op == Average:
                g = lax.pmean(g, axis_name)
            elif op == Sum:
                g = lax.psum(g, axis_name)
            elif op == Max:
                g = lax.pmax(g, axis_name)
            elif op == Min:
                g = lax.pmin(g, axis_name)
            elif op == Adasum:
                from horovod_tpu.ops.adasum import adasum_allreduce
                g = adasum_allreduce(g, axis_name)
            else:
                raise ValueError(
                    f"in-jit gradient reduction with op={op!r} is not "
                    "supported (use Average/Sum/Max/Min/Adasum)")
            return compression.decompress(g, ctx)

        reduced = jax.tree.unflatten(treedef, [reduce_leaf(g)
                                               for g in leaves])
        return (reduced, ef) if ef is not None else reduced

    if ef is not None:
        raise ValueError(
            "ef= residuals are an in-jit concern; the eager tier's int8 "
            "error feedback lives inside the native wire codec "
            "(native/src/codec.cc)")
    if not getattr(compression, "cast_tier", True):
        # Wire-only codec (int8): no framework cast exists — the knob
        # rides the native plane as a per-chunk wire codec instead, so
        # eager and in-jit callers share one setting.
        reduced = api.grouped_allreduce(leaves, name=name, op=op,
                                        compression=compression)
        return jax.tree.unflatten(treedef, list(reduced))
    compressed, ctxs = [], []
    for g in leaves:
        c, ctx = compression.compress(g)
        compressed.append(c)
        ctxs.append(ctx)
    reduced = api.grouped_allreduce(compressed, name=name, op=op)
    out = [compression.decompress(r, ctx) for r, ctx in zip(reduced, ctxs)]
    return jax.tree.unflatten(treedef, out)


def distributed_optimizer(optimizer, *,
                          axis_name: Optional[AxisName] = None,
                          op: ReduceOp = Average,
                          compression=Compression.none,
                          name: str = "distributed_optimizer",
                          backward_passes_per_step: int = 1):
    """Wrap an optax ``GradientTransformation`` so incoming gradients
    are reduced across ranks before the inner update — the optax
    analog of ``hvd.DistributedOptimizer``.

    Use inside ``jit``/``shard_map`` with ``axis_name=...``, or eagerly
    (one process per rank) without.

    ``backward_passes_per_step=N`` enables local gradient aggregation
    (the JAX analog of the reference's
    ``tensorflow/gradient_aggregation.py:16`` and the torch wrapper's
    same-named knob): gradients are summed LOCALLY for N calls and
    reduced across ranks only on every N-th — one collective per N
    microbatches. Non-boundary calls emit zero updates (parameters and
    inner optimizer state advance only on the boundary), so
    ``optax.apply_updates`` can run unconditionally every microbatch.
    The boundary update equals one big-batch update on the SUM of the
    local microbatch gradients, matching the torch tier (average the
    loss over the N passes, or scale the LR, exactly as with the
    reference).
    """
    import optax

    from horovod_tpu import compression as compression_lib

    if backward_passes_per_step < 1:
        raise ValueError("backward_passes_per_step must be >= 1")

    # In-jit int8 threads rank-local error-feedback residuals as
    # explicit optimizer-state leaves (the mesh-plane analog of the
    # wire codec's EF slabs): state grows an "ef" pytree of f32 zeros
    # and every reduce consumes/produces it, so int8 rounding error
    # telescopes across steps instead of compounding.
    use_ef = (axis_name is not None
              and compression_lib.needs_error_feedback(compression))

    def reduce_grads(grads, ef=None):
        return allreduce_gradients(
            grads, axis_name=axis_name, op=op, compression=compression,
            name=name, ef=ef)

    def init_ef(params):
        import jax
        import jax.numpy as jnp
        from horovod_tpu.common.jax_compat import pcast_varying
        axes = ((axis_name,) if isinstance(axis_name, str)
                else tuple(axis_name))
        return jax.tree.map(
            lambda p: pcast_varying(jnp.zeros(p.shape, jnp.float32), axes),
            params)

    if backward_passes_per_step == 1:
        if use_ef:
            def init_fn(params):
                return {"inner": optimizer.init(params),
                        "ef": init_ef(params)}

            def update_fn(updates, state, params=None, **extra):
                reduced, new_ef = reduce_grads(updates, state["ef"])
                out, inner = optimizer.update(reduced, state["inner"],
                                              params, **extra)
                return out, {"inner": inner, "ef": new_ef}

            return optax.GradientTransformation(init_fn, update_fn)

        def init_fn(params):
            return optimizer.init(params)

        def update_fn(updates, state, params=None, **extra):
            return optimizer.update(reduce_grads(updates), state, params,
                                    **extra)

        return optax.GradientTransformation(init_fn, update_fn)

    import jax
    import jax.numpy as jnp

    n = backward_passes_per_step

    def _pvary_missing(t):
        """Promote every leaf to device-varying over ``axis_name``
        (no-op leaf-wise where already varying, or outside a manual-
        axes trace). Keeps the accumulator's VMA type STABLE between
        init and update so the canonical lax.scan-over-microbatches
        carry typechecks."""
        if axis_name is None:
            return t
        from jax import lax
        axes = ((axis_name,) if isinstance(axis_name, str)
                else tuple(axis_name))

        def one(a):
            if not hasattr(jax, "typeof"):
                return a  # legacy jax: no VMA types to stabilise
            vma = getattr(jax.typeof(a), "vma", None)
            if vma is None:
                return a
            missing = tuple(ax for ax in axes if ax not in vma)
            if not missing:
                return a
            try:
                return lax.pcast(a, missing, to="varying")
            except Exception:  # outside shard_map: axis not in scope
                return a
        return jax.tree.map(one, t)

    def init_acc(params):
        state = {"inner": optimizer.init(params),
                 "acc": _pvary_missing(
                     jax.tree.map(jnp.zeros_like, params)),
                 "count": jnp.zeros((), jnp.int32)}
        if use_ef:
            state["ef"] = init_ef(params)
        return state

    def boundary_update(acc, inner, ef, params, extra):
        if use_ef:
            reduced, ef = reduce_grads(acc, ef)
        else:
            reduced = reduce_grads(acc)
        new_updates, new_inner = optimizer.update(
            reduced, inner, params, **extra)
        zero_acc = jax.tree.map(jnp.zeros_like, acc)
        return new_updates, zero_acc, new_inner, ef

    def update_acc(updates, state, params=None, **extra):
        acc = _pvary_missing(
            jax.tree.map(jnp.add, state["acc"], updates))
        count = state["count"] + 1
        ef = state.get("ef")

        if axis_name is None:
            # Eager tier: concrete control flow (the native-runtime
            # collective is a host call and cannot live under lax.cond).
            if int(count) >= n:
                out, acc, inner, ef = boundary_update(
                    acc, state["inner"], ef, params, extra)
                count = jnp.zeros((), jnp.int32)
            else:
                out = jax.tree.map(jnp.zeros_like, updates)
                inner = state["inner"]
        else:
            # In-jit tier: both branches trace; `count` is replicated
            # so every rank takes the same one and the collectives in
            # the boundary branch stay SPMD-legal.
            from jax import lax

            def hold(acc, inner, ef):
                # FRESH-constant zeros, not zeros_like(acc): constants
                # are replicated under VMA typing, matching the
                # boundary branch's post-reduction updates — and the
                # emitted zero updates keep params replicated, exactly
                # like the N=1 path. (zeros_like would inherit acc's
                # device-varying type and poison params' VMA.)
                zeros = jax.tree.map(
                    lambda a: jnp.zeros(a.shape, a.dtype), acc)
                return zeros, acc, inner, ef

            out, acc, inner, ef = lax.cond(
                count >= n,
                lambda a, i, e: boundary_update(a, i, e, params, extra),
                hold, acc, state["inner"], ef)
            count = jnp.where(count >= n, 0, count)

        new_state = {"inner": inner, "acc": acc, "count": count}
        if use_ef:
            new_state["ef"] = ef
        return out, new_state

    return optax.GradientTransformation(init_acc, update_acc)


def distributed_value_and_grad(fun: Callable, argnums=0, *,
                               has_aux: bool = False,
                               axis_name: Optional[AxisName] = None,
                               op: ReduceOp = Average,
                               compression=Compression.none,
                               name: str = "distributed_grad") -> Callable:
    """``jax.value_and_grad`` whose gradients arrive pre-reduced across
    ranks — the ``DistributedGradientTape`` analog (reference
    ``tensorflow/__init__.py:723-790``).

    In-jit tier: the *loss itself* is reduced over ``axis_name``
    (``pmean`` for Average, ``psum`` for Sum) and autodiff then yields
    the exactly-corresponding global gradients — the VMA-correct way to
    express data-parallel training under ``shard_map`` (an explicit
    psum of replicated-param cotangents would double-count).
    Eager tier: local grads are computed, then group-allreduced.
    """
    import jax

    if axis_name is not None:
        from jax import lax
        if op not in (Average, Sum):
            raise ValueError(
                "in-jit distributed_value_and_grad supports Average/Sum")

        from horovod_tpu.common import jax_compat
        from horovod_tpu import compression as compression_lib

        if (not jax_compat.HAS_VMA
                or compression_lib.in_jit_codec(compression) != "none"):
            # Legacy jax: without VMA-typed transposes, grad-of-pmean
            # does not propagate the averaged cotangent back to
            # replicated params. Take the explicit formulation —
            # local grads, then reduce both loss and grads (the
            # reduce_leaf legacy branch always psums). Compression
            # takes the same route on ANY jax: grads must exist
            # explicitly before the collective for the quantized
            # reduce-scatter + all-gather to ride them (autodiff of a
            # pmean'd loss never materializes an interceptable
            # gradient allreduce).
            lvg = jax.value_and_grad(fun, argnums=argnums,
                                     has_aux=has_aux)

            def legacy_wrapped(*args, **kwargs):
                value, grads = lvg(*args, **kwargs)
                loss = value[0] if has_aux else value
                loss = (lax.pmean(loss, axis_name) if op == Average
                        else lax.psum(loss, axis_name))
                value = (loss, value[1]) if has_aux else loss
                grads = allreduce_gradients(
                    grads, axis_name=axis_name, op=op,
                    compression=compression, name=name)
                return value, grads

            return legacy_wrapped

        def global_fun(*args, **kwargs):
            out = fun(*args, **kwargs)
            if has_aux:
                loss, aux = out
            else:
                loss, aux = out, None
            loss = (lax.pmean(loss, axis_name) if op == Average
                    else lax.psum(loss, axis_name))
            return (loss, aux) if has_aux else loss

        return jax.value_and_grad(global_fun, argnums=argnums,
                                  has_aux=has_aux)

    vg = jax.value_and_grad(fun, argnums=argnums, has_aux=has_aux)

    def wrapped(*args, **kwargs):
        value, grads = vg(*args, **kwargs)
        grads = allreduce_gradients(
            grads, axis_name=axis_name, op=op, compression=compression,
            name=name)
        return value, grads

    return wrapped


def broadcast_parameters(params: Any, root_rank: int = 0,
                         name: str = "broadcast_parameters") -> Any:
    """Broadcast a parameter pytree from ``root_rank``; returns the
    synced pytree (functional — jax arrays are immutable, unlike the
    reference's in-place ``torch/functions.py:29``)."""
    import jax

    leaves, treedef = jax.tree.flatten(params)
    handles = [api.broadcast_async(leaf, root_rank=root_rank,
                                   name=f"{name}.{i}")
               for i, leaf in enumerate(leaves)]
    synced = []
    for leaf, h in zip(leaves, handles):
        out = api.synchronize(h)
        synced.append(out.reshape(leaf.shape) if hasattr(out, "reshape")
                      else out)
    return jax.tree.unflatten(treedef, synced)


def sync_batch_norm(x, *, axis_name: AxisName = "dp",
                    scale=None, bias=None, eps: float = 1e-5,
                    reduce_dims=None):
    """Normalize ``x`` with batch statistics taken over BOTH the local
    reduce dims and the ``axis_name`` mesh axis — the in-jit SPMD analog
    of the reference's SyncBatchNorm (``torch/sync_batch_norm.py:22``,
    ``tensorflow/sync_batch_norm.py:22``). Call inside
    ``shard_map``/``pjit``; stats ride two small ``psum``\\ s that XLA
    fuses into one.

    ``reduce_dims`` defaults to all dims except the last (channel).
    Returns ``(y, mean, var)`` so callers can maintain running stats.
    For flax models, ``flax.linen.BatchNorm(axis_name="dp")`` achieves
    the same inside ``pjit`` — this helper is the framework-free form.
    """
    import jax.numpy as jnp
    from jax import lax

    if reduce_dims is None:
        reduce_dims = tuple(range(x.ndim - 1))
    reduce_dims = tuple(d % x.ndim for d in reduce_dims)
    h = x.astype(jnp.float32)
    n_local = 1
    for d in reduce_dims:
        n_local *= x.shape[d]
    stats = jnp.stack([jnp.sum(h, axis=reduce_dims),
                       jnp.sum(h * h, axis=reduce_dims)])
    stats = lax.psum(stats, axis_name)
    from horovod_tpu.ops.collectives import axis_size
    n = n_local * axis_size(axis_name)
    mean = stats[0] / n
    var = stats[1] / n - mean * mean
    # Broadcast stats back to x's layout: kept (channel) dims stay,
    # reduced dims become 1 — so NCHW-style reduce_dims=(0, 2, 3)
    # works, not just channels-last.
    bshape = [1 if d in reduce_dims else x.shape[d] for d in range(x.ndim)]
    y = (h - mean.reshape(bshape)) * lax.rsqrt(var.reshape(bshape) + eps)
    if scale is not None:
        y = y * scale.astype(jnp.float32).reshape(bshape)
    if bias is not None:
        y = y + bias.astype(jnp.float32).reshape(bshape)
    return y.astype(x.dtype), mean, var
