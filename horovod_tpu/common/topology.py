"""Process topology: rank/size/local/cross coordinates.

Reference: Horovod derives rank/local_rank/cross_rank either from MPI
communicators (``horovod/common/mpi/mpi_controller.cc:30-82``) or from
launcher-provided env vars (``horovod/common/gloo/gloo_context.cc:139-144``,
set by ``runner/gloo_run.py:65-76``). We keep the env-var contract —
``horovodrun`` (ours) sets ``HOROVOD_RANK/SIZE/LOCAL_RANK/LOCAL_SIZE/
CROSS_RANK/CROSS_SIZE`` — and default to a single-process topology.

TPU mapping: one process per host, ``local_size`` = chips on this host,
``cross_size`` = number of hosts in the pod slice. In pure SPMD mode
(one process, N devices) the *device* axis carries parallelism and the
process topology is trivially 1.
"""

from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass(frozen=True)
class Topology:
    rank: int = 0
    size: int = 1
    local_rank: int = 0
    local_size: int = 1
    cross_rank: int = 0
    cross_size: int = 1

    def __post_init__(self):
        if not (0 <= self.rank < self.size):
            raise ValueError(f"rank {self.rank} out of range for size {self.size}")
        if not (0 <= self.local_rank < self.local_size):
            raise ValueError(
                f"local_rank {self.local_rank} out of range for local_size {self.local_size}")
        if not (0 <= self.cross_rank < self.cross_size):
            raise ValueError(
                f"cross_rank {self.cross_rank} out of range for cross_size {self.cross_size}")

    @property
    def is_homogeneous(self) -> bool:
        return self.size == self.local_size * self.cross_size


def _env_int(names, default):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return int(v)
    return default


def topology_from_env() -> Topology:
    """Build topology from launcher env vars (or single-process default)."""
    size = _env_int(["HOROVOD_SIZE", "OMPI_COMM_WORLD_SIZE"], 1)
    rank = _env_int(["HOROVOD_RANK", "OMPI_COMM_WORLD_RANK"], 0)
    local_size = _env_int(["HOROVOD_LOCAL_SIZE", "OMPI_COMM_WORLD_LOCAL_SIZE"], size if size else 1)
    local_rank = _env_int(["HOROVOD_LOCAL_RANK", "OMPI_COMM_WORLD_LOCAL_RANK"], rank)
    cross_size = _env_int(["HOROVOD_CROSS_SIZE"], max(1, size // max(1, local_size)))
    cross_rank = _env_int(["HOROVOD_CROSS_RANK"], rank // max(1, local_size))
    return Topology(rank=rank, size=size, local_rank=local_rank,
                    local_size=local_size, cross_rank=cross_rank, cross_size=cross_size)
