"""Reduction op constants.

Reference: ``horovod/common/basics.py`` exposes Average/Sum/Adasum;
``horovod/common/message.h`` carries the reduce op on the wire. We add
Min/Max/Product which XLA provides for free (``lax.pmin``/``pmax``)."""

import enum


class ReduceOp(enum.IntEnum):
    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT
