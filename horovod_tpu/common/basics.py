"""ctypes bridge to the native coordination core.

Rebuild of the reference's ``horovod/common/basics.py:33-288``
(``HorovodBasics``): loads the shared library, declares the C ABI
signatures, and exposes init/shutdown/rank/size plus the raw enqueue
surface consumed by :mod:`horovod_tpu.runtime`.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_LIB_CANDIDATES = [
    os.path.join(_REPO_ROOT, "native", "libhorovod_tpu_core.so"),
    os.path.join(os.path.dirname(__file__), "libhorovod_tpu_core.so"),
]

# C ABI op codes (native/include/hvd/message.h RequestType).
OP_ALLREDUCE = 0
OP_ALLGATHER = 1
OP_BROADCAST = 2
OP_ALLTOALL = 3
OP_JOIN = 4
OP_BARRIER = 5
OP_REDUCESCATTER = 6

EXEC_HOST = 0
EXEC_CALLBACK = 1

# Native wire/ABI version pins. These MUST match the constants in
# native/include/hvd/message.h (kAbiVersion / kWireVersion*) — the ABI
# is enforced at library load below, and tests/test_wire_abi.py greps
# the header so a native bump can't silently skew this shim even
# before a rebuild happens.
ABI_VERSION = 15
WIRE_VERSION_REQUEST_LIST = 3
WIRE_VERSION_RESPONSE_LIST = 7

# Metrics snapshot layout version (native/include/hvd/metrics.h
# kMetricsVersion): the packed int64 layout hvd_metrics_snapshot
# writes. Checked at library load AND against the header by
# tests/test_metrics_abi.py, the same two-sided pin as the ABI above.
METRICS_VERSION = 9

# Native WireCodec ids (native/include/hvd/codec.h); -1 = follow the
# job-wide HOROVOD_WIRE_COMPRESSION default.
WIRE_CODEC_DEFAULT = -1
WIRE_CODEC_NONE = 0
WIRE_CODEC_BF16 = 1
WIRE_CODEC_FP16 = 2
WIRE_CODEC_INT8 = 3

# Native CollectiveAlgo ids (native/include/hvd/schedule.h); 0 = follow
# the coordinator's selection table / HOROVOD_COLLECTIVE_ALGO. Name
# order mirrors kCollectiveAlgoNames.
COLLECTIVE_ALGOS = {
    "auto": 0,
    "ring": 1,
    "hd": 2,
    "striped": 3,
    "doubling": 4,
    "hier": 5,
}

# Native AlltoallAlgo ids (native/include/hvd/schedule.h); 0 = follow
# the measured pairwise-vs-bruck verdict / HOROVOD_ALLTOALL_ALGO.
# Name order mirrors kAlltoallAlgoNames.
ALLTOALL_ALGOS = {
    "auto": 0,
    "pairwise": 1,
    "bruck": 2,
}


# Native CollKind ids (native/include/hvd/schedule.h): the collective
# a chunk-op table expresses, for hvd_build_coll_schedule.
COLL_ALLREDUCE = 0
COLL_ALLGATHER = 1
COLL_REDUCESCATTER = 2
COLL_ALLTOALL = 3


def collective_algo_id(algorithm) -> int:
    """Map an ``algorithm=`` kwarg (name string, native id, or None) to
    the native CollectiveAlgo id."""
    if algorithm is None:
        return 0
    if isinstance(algorithm, str):
        try:
            return COLLECTIVE_ALGOS[algorithm]
        except KeyError:
            raise ValueError(
                f"unknown collective algorithm {algorithm!r}; want one of "
                f"{sorted(COLLECTIVE_ALGOS)}") from None
    a = int(algorithm)
    if not 0 <= a < len(COLLECTIVE_ALGOS):
        raise ValueError(f"collective algorithm id {a} out of range")
    return a

# numpy dtype -> native DataType id (native/include/hvd/common.h).
_DTYPE_MAP = {
    np.dtype(np.uint8): 0,
    np.dtype(np.int8): 1,
    np.dtype(np.uint16): 2,
    np.dtype(np.int16): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int64): 5,
    np.dtype(np.float16): 6,
    np.dtype(np.float32): 7,
    np.dtype(np.float64): 8,
    np.dtype(np.bool_): 9,
}
_BFLOAT16_ID = 10


def np_dtype(dt_id: int):
    """Inverse of :func:`dtype_id` (bfloat16 via ml_dtypes)."""
    if dt_id == _BFLOAT16_ID:
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    for dt, i in _DTYPE_MAP.items():
        if i == dt_id:
            return dt
    raise TypeError(f"unknown native dtype id {dt_id}")


def dtype_id(dtype) -> int:
    dtype = np.dtype(dtype) if not hasattr(dtype, "name") else dtype
    if getattr(dtype, "name", "") == "bfloat16":
        return _BFLOAT16_ID
    try:
        return _DTYPE_MAP[np.dtype(dtype)]
    except KeyError:
        raise TypeError(f"unsupported dtype for collective: {dtype}") from None


EXEC_CB_TYPE = ctypes.CFUNCTYPE(
    None, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
    ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32,
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int32, ctypes.c_int32,
    ctypes.c_int32)
ALLOC_CB_TYPE = ctypes.CFUNCTYPE(
    ctypes.c_void_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
    ctypes.c_int32)


def _build_native() -> None:
    # Serialize across processes: concurrently-launched ranks all try to
    # (re)build on import, and an unlocked parallel make could relink
    # the .so while a sibling rank is dlopen()ing it.
    import fcntl
    native_dir = os.path.join(_REPO_ROOT, "native")
    with open(os.path.join(native_dir, ".build.lock"), "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        subprocess.run(["make", "-C", native_dir, "-j"],
                       check=True, capture_output=True)


def load_library() -> ctypes.CDLL:
    # HOROVOD_NATIVE_LIB points the loader at an alternate build of the
    # core — the sanitizer variants (libhorovod_tpu_core.tsan.so, ...)
    # from `make -C native SAN=...` — so the exact same Python test
    # scenarios run against an instrumented library
    # (docs/development.md, tests/test_sanitizers.py). The override is
    # explicit opt-in: no rebuild is attempted (the harness that set it
    # owns the build), but the ABI pin below still applies, so a stale
    # instrumented .so cannot silently skew results.
    override = os.environ.get("HOROVOD_NATIVE_LIB")
    if override:
        if not os.path.exists(override):
            raise OSError(
                f"HOROVOD_NATIVE_LIB={override} does not exist; build it "
                "first (e.g. make -C native SAN=tsan)")
        return _declare_abi(ctypes.CDLL(override), override)
    path = next((p for p in _LIB_CANDIDATES if os.path.exists(p)), None)
    # Always (re)run make when the source tree is present: make is a
    # no-op when the .so is current, and this keeps stale binaries from
    # silently shadowing native source edits.
    if os.path.exists(os.path.join(_REPO_ROOT, "native", "Makefile")):
        try:
            _build_native()
            path = next(p for p in _LIB_CANDIDATES if os.path.exists(p))
        except Exception as e:
            if path is None:
                raise
            # A stale prebuilt .so may predate ABI changes in this source
            # tree — fall back only after the version check below
            # confirms compatibility, and never silently.
            import warnings
            warnings.warn(
                f"horovod_tpu: rebuilding the native core failed ({e}); "
                f"falling back to existing {path}, which may be stale",
                RuntimeWarning)
    elif path is None:
        raise OSError("horovod_tpu native core not found and no source tree "
                      "to build it from")
    return _declare_abi(ctypes.CDLL(path), path)


def _declare_abi(lib: ctypes.CDLL, path: str) -> ctypes.CDLL:
    """Declare the C ABI signatures and enforce the version pins on an
    already-dlopen'd core (shared between the default candidate search
    and the HOROVOD_NATIVE_LIB override path)."""
    try:
        got = lib.hvd_abi_version()
    except AttributeError:
        got = -1
    if got != ABI_VERSION:
        raise OSError(
            f"horovod_tpu native core at {path} has ABI version {got}, "
            f"expected {ABI_VERSION}; rebuild it (make -C native)")

    lib.hvd_init.restype = ctypes.c_int
    lib.hvd_init.argtypes = [ctypes.c_int] * 6
    lib.hvd_shutdown.restype = None
    for fn in ("hvd_initialized", "hvd_rank", "hvd_size", "hvd_local_rank",
               "hvd_local_size", "hvd_cross_rank", "hvd_cross_size",
               "hvd_is_homogeneous"):
        getattr(lib, fn).restype = ctypes.c_int
    lib.hvd_enqueue.restype = ctypes.c_int64
    lib.hvd_enqueue.argtypes = [
        ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_double,
        ctypes.c_double, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.c_int, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ctypes.c_int,
    ]
    lib.hvd_last_enqueue_error.restype = ctypes.c_char_p
    lib.hvd_join.restype = ctypes.c_int64
    lib.hvd_barrier.restype = ctypes.c_int64
    lib.hvd_poll.restype = ctypes.c_int
    lib.hvd_poll.argtypes = [ctypes.c_int64]
    lib.hvd_wait.restype = ctypes.c_int
    lib.hvd_wait.argtypes = [ctypes.c_int64, ctypes.c_int, ctypes.c_char_p,
                             ctypes.c_int]
    lib.hvd_release_handle.restype = None
    lib.hvd_release_handle.argtypes = [ctypes.c_int64]
    lib.hvd_get_recvsplits.restype = ctypes.c_int
    lib.hvd_get_recvsplits.argtypes = [ctypes.c_int64,
                                       ctypes.POINTER(ctypes.c_int64),
                                       ctypes.c_int]
    lib.hvd_exec_done.restype = None
    lib.hvd_exec_done.argtypes = [ctypes.c_int64, ctypes.c_int,
                                  ctypes.c_char_p]
    lib.hvd_set_exec_callback.restype = None
    lib.hvd_set_exec_callback.argtypes = [EXEC_CB_TYPE]
    lib.hvd_set_alloc_callback.restype = None
    lib.hvd_set_alloc_callback.argtypes = [ALLOC_CB_TYPE]
    # Returns 0 on success, -1 when the timeline file cannot be opened
    # (surfaced as a Python exception in runtime.start_timeline). A
    # second call on a running timeline restarts it onto the new path.
    lib.hvd_start_timeline.restype = ctypes.c_int
    lib.hvd_start_timeline.argtypes = [ctypes.c_char_p]
    lib.hvd_stop_timeline.restype = None
    lib.hvd_pending_count.restype = ctypes.c_int64
    # Metrics registry (docs/observability.md): versioned packed
    # snapshot + name/kind tables, consumed by horovod_tpu/metrics.py.
    lib.hvd_metrics_snapshot.restype = ctypes.c_int64
    lib.hvd_metrics_snapshot.argtypes = [ctypes.POINTER(ctypes.c_int64),
                                         ctypes.c_int64]
    for fn in ("hvd_metrics_version", "hvd_metrics_num_counters",
               "hvd_metrics_num_hists", "hvd_metrics_hist_buckets",
               "hvd_metrics_enabled"):
        getattr(lib, fn).restype = ctypes.c_int
    lib.hvd_metrics_counter_name.restype = ctypes.c_char_p
    lib.hvd_metrics_counter_name.argtypes = [ctypes.c_int]
    lib.hvd_metrics_counter_kind.restype = ctypes.c_int
    lib.hvd_metrics_counter_kind.argtypes = [ctypes.c_int]
    lib.hvd_metrics_hist_name.restype = ctypes.c_char_p
    lib.hvd_metrics_hist_name.argtypes = [ctypes.c_int]
    lib.hvd_metrics_reset.restype = None
    lib.hvd_metrics_set_enabled.restype = None
    lib.hvd_metrics_set_enabled.argtypes = [ctypes.c_int]
    lib.hvd_metrics_test_add.restype = None
    lib.hvd_metrics_test_add.argtypes = [ctypes.c_int, ctypes.c_int64]
    lib.hvd_metrics_test_observe.restype = None
    lib.hvd_metrics_test_observe.argtypes = [ctypes.c_int, ctypes.c_int64]
    # Stall findings beyond the log (hvd.stalled_tensors()): returns the
    # byte count needed including the NUL, copies at most len-1 bytes.
    lib.hvd_stalled_tensors.restype = ctypes.c_int
    lib.hvd_stalled_tensors.argtypes = [ctypes.c_char_p, ctypes.c_int]
    # Flight recorder (native/include/hvd/flight.h): always-on
    # control-plane event ring with postmortem dump. Snapshot follows
    # the stalled_tensors size-probe protocol.
    lib.hvd_flight_record.restype = None
    lib.hvd_flight_record.argtypes = [ctypes.c_int, ctypes.c_longlong,
                                      ctypes.c_longlong]
    lib.hvd_flight_snapshot.restype = ctypes.c_longlong
    lib.hvd_flight_snapshot.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
    lib.hvd_flight_dump.restype = ctypes.c_int
    lib.hvd_flight_dump.argtypes = [ctypes.c_char_p]
    lib.hvd_flight_install.restype = ctypes.c_int
    lib.hvd_flight_install.argtypes = [ctypes.c_char_p]
    lib.hvd_flight_num_events.restype = ctypes.c_int
    lib.hvd_flight_event_name.restype = ctypes.c_char_p
    lib.hvd_flight_event_name.argtypes = [ctypes.c_int]
    lib.hvd_flight_count.restype = ctypes.c_longlong
    lib.hvd_flight_clear.restype = None
    lib.hvd_flight_set_enabled.restype = None
    lib.hvd_flight_set_enabled.argtypes = [ctypes.c_int]
    lib.hvd_flight_enabled.restype = ctypes.c_int
    got_metrics = lib.hvd_metrics_version()
    if got_metrics != METRICS_VERSION:
        raise OSError(
            f"horovod_tpu native core at {path} has metrics snapshot "
            f"version {got_metrics}, expected {METRICS_VERSION}; rebuild "
            "it (make -C native)")
    # Host reduction kernels + thread budget (perf_tuning.md): exercised
    # directly by the dtype-coverage tests and exposed through
    # hvd.set_reduce_threads / hvd.reduce_threads.
    lib.hvd_host_accumulate.restype = None
    lib.hvd_host_accumulate.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64]
    lib.hvd_host_scale.restype = None
    lib.hvd_host_scale.argtypes = [ctypes.c_int, ctypes.c_void_p,
                                   ctypes.c_int64, ctypes.c_double]
    lib.hvd_set_reduce_threads.restype = None
    lib.hvd_set_reduce_threads.argtypes = [ctypes.c_int]
    lib.hvd_reduce_threads.restype = ctypes.c_int
    # Vectored-transport surface (ABI v8, docs/perf_tuning.md
    # zero-copy transport): real SendV/RecvV/frame paths over
    # caller-owned fds — the socketpair unit-test surface
    # (tests/test_transport.py) plus the resolved-mode probes bench.py
    # reports alongside the busbw arms.
    lib.hvd_tcp_sendv.restype = ctypes.c_int
    lib.hvd_tcp_sendv.argtypes = [ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_void_p),
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.c_int]
    lib.hvd_tcp_recvv.restype = ctypes.c_int
    lib.hvd_tcp_recvv.argtypes = [ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_void_p),
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.c_int]
    lib.hvd_tcp_send_frame.restype = ctypes.c_int
    lib.hvd_tcp_send_frame.argtypes = [ctypes.c_int, ctypes.c_void_p,
                                       ctypes.c_uint64]
    lib.hvd_tcp_recv_frame.restype = ctypes.c_int64
    lib.hvd_tcp_recv_frame.argtypes = [ctypes.c_int, ctypes.c_void_p,
                                       ctypes.c_uint64]
    lib.hvd_tcp_transport_mode.restype = ctypes.c_int
    lib.hvd_tcp_transport_mode_name.restype = ctypes.c_char_p
    # Transport riders (ABI v10): io_uring submission-batching verdict
    # (HOROVOD_TCP_IOURING end-to-end probe) and the WorkerPool
    # affinity gauge (HOROVOD_REDUCE_THREAD_AFFINITY pinned-thread
    # count).
    lib.hvd_tcp_iouring_mode.restype = ctypes.c_int
    lib.hvd_tcp_iouring_mode_name.restype = ctypes.c_char_p
    lib.hvd_worker_affinity.restype = ctypes.c_int
    # Steady-state schedule lock (ABI v11, docs/perf_tuning.md
    # "Steady-state schedule lock"): the engaged flag plus the period-
    # detector test hooks tests/test_steady_lock.py drives without
    # spawning ranks.
    lib.hvd_steady_lock_engaged.restype = ctypes.c_int
    # Persistent locked data plane (ABI v13, docs/perf_tuning.md
    # "Persistent locked data plane"): the coordinator-synced
    # HOROVOD_STEADY_PERSISTENT verdict (0 = auto, 1 = off) and the
    # live pre-posted recv buffer count (the tcp_prepost_buffers
    # gauge's backing store).
    lib.hvd_steady_persistent.restype = ctypes.c_int
    lib.hvd_tcp_prepost_buffers.restype = ctypes.c_int64
    lib.hvd_lockdet_create.restype = ctypes.c_void_p
    lib.hvd_lockdet_feed.restype = None
    lib.hvd_lockdet_feed.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                     ctypes.c_char_p]
    lib.hvd_lockdet_ready.restype = ctypes.c_int
    lib.hvd_lockdet_ready.argtypes = [ctypes.c_void_p]
    lib.hvd_lockdet_period.restype = ctypes.c_int
    lib.hvd_lockdet_period.argtypes = [ctypes.c_void_p]
    lib.hvd_lockdet_take.restype = ctypes.c_int
    lib.hvd_lockdet_take.argtypes = [ctypes.c_void_p]
    lib.hvd_lockdet_destroy.restype = None
    lib.hvd_lockdet_destroy.argtypes = [ctypes.c_void_p]
    # Wire-codec kernels (perf_tuning.md HOROVOD_WIRE_COMPRESSION):
    # exercised directly by the codec round-trip/error-feedback tests.
    lib.hvd_wire_encoded_bytes.restype = ctypes.c_int64
    lib.hvd_wire_encoded_bytes.argtypes = [ctypes.c_int, ctypes.c_int64]
    lib.hvd_wire_encode.restype = None
    lib.hvd_wire_encode.argtypes = [ctypes.c_int, ctypes.c_void_p,
                                    ctypes.c_int64, ctypes.c_void_p,
                                    ctypes.c_void_p]
    lib.hvd_wire_decode.restype = None
    lib.hvd_wire_decode.argtypes = [ctypes.c_int, ctypes.c_void_p,
                                    ctypes.c_int64, ctypes.c_void_p]
    lib.hvd_wire_decode_add.restype = None
    lib.hvd_wire_decode_add.argtypes = [ctypes.c_int, ctypes.c_void_p,
                                        ctypes.c_int64, ctypes.c_void_p]
    # Schedule-interpreter surface (docs/perf_tuning.md "Collective
    # algorithm selection"): chunk-op table builder + the default
    # selection table, both pure functions — the simulator tests and
    # bench.py's table dump drive them without spawning ranks.
    lib.hvd_build_schedule.restype = ctypes.c_int
    lib.hvd_build_schedule.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
    lib.hvd_algo_select.restype = ctypes.c_int
    lib.hvd_algo_select.argtypes = [ctypes.c_int64, ctypes.c_int,
                                    ctypes.c_int, ctypes.c_int64]
    lib.hvd_algo_name.restype = ctypes.c_char_p
    lib.hvd_algo_name.argtypes = [ctypes.c_int]
    lib.hvd_collective_algo.restype = ctypes.c_int
    # Measured-topology surface (ABI v9, docs/perf_tuning.md "Measured
    # topology & schedule synthesis"): the alpha-beta link model, the
    # on-demand re-probe, the measured selection verdict, the native
    # cost walk, and the any-collective table builder tools/synth.py
    # and the promoted verifier enumerate.
    lib.hvd_topology.restype = ctypes.c_int
    lib.hvd_topology.argtypes = [ctypes.POINTER(ctypes.c_double),
                                 ctypes.POINTER(ctypes.c_double),
                                 ctypes.c_int]
    lib.hvd_topology_probe.restype = ctypes.c_double
    lib.hvd_topology_probe.argtypes = []
    lib.hvd_algo_select_measured.restype = ctypes.c_int
    lib.hvd_algo_select_measured.argtypes = [ctypes.c_int64, ctypes.c_int,
                                             ctypes.c_int, ctypes.c_int64]
    lib.hvd_algo_cost_us.restype = ctypes.c_double
    lib.hvd_algo_cost_us.argtypes = [ctypes.c_int, ctypes.c_int64,
                                     ctypes.c_int, ctypes.c_int,
                                     ctypes.c_int]
    # Point-to-point migration pricing (docs/serving.md "Direct
    # migration"): the native half of the serving router's cost twin
    # (horovod_tpu/serve/migrate.py mirrors both formulas); <0 when no
    # model. The sanitizer tier cross-checks native vs twin.
    lib.hvd_link_cost_us.restype = ctypes.c_double
    lib.hvd_link_cost_us.argtypes = [ctypes.c_int, ctypes.c_int,
                                     ctypes.c_int64]
    lib.hvd_migration_cost_us.restype = ctypes.c_double
    lib.hvd_migration_cost_us.argtypes = [ctypes.c_int, ctypes.c_int,
                                          ctypes.c_int64, ctypes.c_int64]
    lib.hvd_build_coll_schedule.restype = ctypes.c_int
    lib.hvd_build_coll_schedule.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
    # Membership plane (ABI v12, docs/elastic.md): the process-global
    # epoch / active-rank / fence surface hvd.membership() reads, plus
    # the decay blacklist the elastic driver and serving router share.
    # Usable BEFORE hvd_init — driver/router processes never init the
    # core.
    lib.hvd_membership_epoch.restype = ctypes.c_int64
    lib.hvd_membership_generation.restype = ctypes.c_int64
    lib.hvd_membership_size.restype = ctypes.c_int
    lib.hvd_membership_ranks.restype = ctypes.c_int
    lib.hvd_membership_ranks.argtypes = [ctypes.POINTER(ctypes.c_int),
                                         ctypes.c_int]
    lib.hvd_membership_advance.restype = ctypes.c_int64
    lib.hvd_membership_advance.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.hvd_membership_reset.restype = None
    lib.hvd_membership_reset.argtypes = [ctypes.c_int64, ctypes.c_int]
    lib.hvd_membership_fence_count.restype = ctypes.c_int
    lib.hvd_blacklist_configure.restype = None
    lib.hvd_blacklist_configure.argtypes = [ctypes.c_double, ctypes.c_double]
    lib.hvd_blacklist_record.restype = ctypes.c_double
    lib.hvd_blacklist_record.argtypes = [ctypes.c_char_p, ctypes.c_double]
    lib.hvd_blacklist_weight.restype = ctypes.c_double
    lib.hvd_blacklist_weight.argtypes = [ctypes.c_char_p, ctypes.c_double]
    lib.hvd_blacklist_check.restype = ctypes.c_int
    lib.hvd_blacklist_check.argtypes = [ctypes.c_char_p, ctypes.c_double]
    lib.hvd_blacklist_count.restype = ctypes.c_int
    lib.hvd_blacklist_count.argtypes = [ctypes.c_double]
    lib.hvd_blacklist_clear.restype = None
    # Topology staleness hooks (ABI v12): keyless model injection + the
    # auto-resolution verdict, the test surface pinning ResolveAlgoAuto's
    # refuse-stale-hostkey rule.
    lib.hvd_topology_inject.restype = ctypes.c_int
    lib.hvd_topology_inject.argtypes = [ctypes.c_char_p]
    lib.hvd_algo_resolve_auto.restype = ctypes.c_int
    lib.hvd_algo_resolve_auto.argtypes = [ctypes.c_int64, ctypes.c_int,
                                          ctypes.c_int]
    return lib

# Membership change reasons (native/include/hvd/membership.h
# MembershipChangeReason — stable ints, part of the ABI surface).
MEMBER_RESET = 0
MEMBER_JOIN = 1
MEMBER_DEAD_PEER = 2
MEMBER_SHRINK = 3

# Flight-recorder event ids (native/include/hvd/flight.h FlightEvent —
# stable ints, part of the ABI surface; only the ones Python records
# are named here, pinned against the native name table by
# tests/test_flight.py).
FLIGHT_PEER_DEATH = 6
FLIGHT_REQUEUE = 10
FLIGHT_INTERNAL_ERROR = 11


_lib: Optional[ctypes.CDLL] = None


def get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = load_library()
    return _lib
