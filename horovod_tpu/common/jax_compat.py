"""Compatibility layer across JAX API generations.

The framework is written against the modern surface (``jax.shard_map``
with ``axis_names=``/``check_vma=``, ``lax.axis_size``, ``lax.pcast``),
but deployment containers pin older jaxlibs where ``shard_map`` still
lives in ``jax.experimental`` with the ``auto=``/``check_rep=``
spelling and the VMA (varying-manual-axes) type system does not exist
yet. Every module routes through this shim instead of feature-testing
inline, so the mapping lives in exactly one place:

==============================  =================================
modern API                      legacy (<= 0.4.x) equivalent
==============================  =================================
``jax.shard_map``               ``jax.experimental.shard_map``
``axis_names={...}``            ``auto = mesh.axis_names - {...}``
``check_vma=b``                 ``check_rep=b``
``lax.axis_size(name)``         ``lax.psum(1, name)`` (static)
``lax.pcast(x, axes, ...)``     no-op (no VMA types to declare)
==============================  =================================
"""

from __future__ import annotations

import math

import jax
from jax import lax as _lax

try:
    from jax import shard_map as _new_shard_map  # jax >= 0.6
    HAS_NEW_SHARD_MAP = True
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _old_shard_map
    HAS_NEW_SHARD_MAP = False

HAS_VMA = hasattr(_lax, "pcast")


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma: bool = True):
    """Drop-in for modern ``jax.shard_map`` keyword usage.

    ``axis_names`` is the MANUAL axis subset (modern semantics); on
    legacy jax it is translated to the complementary ``auto`` set.
    ``check_vma`` maps to legacy ``check_rep``.
    """
    if HAS_NEW_SHARD_MAP:
        kw = {"check_vma": check_vma}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return _new_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)
    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    # check_rep stays OFF on legacy regardless of check_vma: the old
    # replication checker predates the VMA type system and rejects
    # valid programs (cond branches, psum-of-masked, grad-through-
    # shard_map) — its own error message recommends check_rep=False.
    # It is a static verifier only; numerics are unaffected.
    return _old_shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False,
                          auto=auto)


def axis_size(axis_name) -> int:
    """Static size of a named mapped axis (modern ``lax.axis_size``).

    Legacy fallback: ``lax.psum`` of a non-tracer constant folds to
    the axis size at trace time — the historical idiom.
    """
    if hasattr(_lax, "axis_size"):
        if isinstance(axis_name, (tuple, list)):
            return math.prod(_lax.axis_size(a) for a in axis_name)
        return _lax.axis_size(axis_name)
    if isinstance(axis_name, (tuple, list)):
        axis_name = tuple(axis_name)
    return _lax.psum(1, axis_name)


def supports_narrow_psum_scatter() -> bool:
    """Whether a sub-f32 ``lax.psum_scatter`` is safe to lower here.

    Legacy XLA-CPU's ``AllReducePromotion`` pass hard-ABORTS on sub-f32
    reduction-collective operands (the same crash the embed island and
    pipeline.py work around with f32 wires); modern jax/XLA rewrites
    them instead. The quantized reduce-scatter therefore only takes the
    psum_scatter-native bf16/fp16 hop when the jax generation is modern
    or the backend is not CPU — everywhere else it keeps the
    all_to_all + f32-fold spelling (same wire bytes, no native reduce).
    """
    return HAS_NEW_SHARD_MAP or jax.default_backend() != "cpu"


def pcast_varying(x, axes):
    """Declare ``x`` varying over manual ``axes`` where the VMA type
    system exists; identity on legacy jax (nothing to declare)."""
    if not axes:
        return x
    if HAS_VMA:
        return _lax.pcast(x, tuple(axes), to="varying")
    return x


def vma_of(x) -> frozenset:
    """The varying-manual-axes set of ``x``'s type (empty on legacy
    jax, where every shard_map value is implicitly varying)."""
    if not HAS_VMA or not hasattr(jax, "typeof"):
        return frozenset()
    return frozenset(getattr(jax.typeof(x), "vma", frozenset()))
