"""Framework exceptions.

Mirrors the reference's ``horovod/common/exceptions.py``: a failed
collective raises :class:`HorovodInternalError` (caught by the elastic
runner to trigger state restore + re-rendezvous), and a host-membership
change surfaces as :class:`HostsUpdatedInterrupt` at commit points
(reference ``horovod/common/elastic.py:60-96``).
"""


class HorovodInternalError(RuntimeError):
    """Internal error raised when a collective operation fails.

    Under elastic training this is recoverable: state is restored from
    the last commit and the job re-rendezvouses.

    When ``HOROVOD_FLIGHT_DIR`` is set, constructing one records an
    ``internal_error`` flight event and dumps the native flight ring —
    the failure that triggers a restore is exactly the moment the
    control-plane trail matters (see ``docs/observability.md``).
    """

    def __init__(self, *args):
        super().__init__(*args)
        import os
        if os.environ.get("HOROVOD_FLIGHT_DIR"):
            try:
                from horovod_tpu.common import basics
                from horovod_tpu.metrics import flight_dump, flight_record
                flight_record(basics.FLIGHT_INTERNAL_ERROR)
                flight_dump()
            except Exception:
                pass  # never let telemetry mask the real failure


class HostsUpdatedInterrupt(Exception):
    """Raised asynchronously (at commit/sync points) when the set of
    available hosts changed and the job should re-initialize."""

    def __init__(self, skip_sync: bool = False):
        super().__init__()
        self.skip_sync = skip_sync


class WorkerExcludedError(SystemExit):
    """This worker's slot is not part of the new elastic assignment; the
    process exits cleanly (code 0) so the driver does not count it as a
    failure."""

    def __init__(self, reason: str = ""):
        super().__init__(0)
        self.reason = reason


class TensorShapeError(ValueError):
    """Cross-rank tensor shape/dtype mismatch detected by the controller
    (reference ``controller.cc:471-748`` produces an ERROR response)."""
