from horovod_tpu.ops.collectives import (  # noqa: F401
    allreduce,
    grouped_allreduce,
    allgather,
    broadcast,
    alltoall,
    reducescatter,
    ring_permute,
    axis_rank,
    axis_size,
)
from horovod_tpu.ops.quantized import (  # noqa: F401
    quantized_allreduce,
    quantized_allgather,
)
