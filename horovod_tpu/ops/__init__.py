from horovod_tpu.ops.collectives import (  # noqa: F401
    allreduce,
    grouped_allreduce,
    allgather,
    broadcast,
    alltoall,
    reducescatter,
    ring_permute,
    axis_rank,
    axis_size,
)
