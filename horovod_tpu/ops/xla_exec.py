"""XLA executor for eager CALLBACK-mode responses.

The NCCL-ops analog (reference ``horovod/common/ops/nccl_operations.cc``):
the native controller decides *when* and *in what order* a fused batch
runs; this module decides *how* — by launching a jitted XLA program.

Process topologies:

* size == 1: collectives over ranks degenerate to (scaled) identity —
  jitted so dtype/scale semantics match the distributed path exactly.
* multi-process under ``jax.distributed`` with one device per process
  (brought up by ``hvd.init()`` when ``HOROVOD_XLA_EXEC=1`` /
  ``horovodrun --xla-exec``): every op in the matrix — allreduce
  (fused batches), allgather (uneven rows), broadcast, alltoall (with
  splits), reducescatter — runs as a jitted global-array program over a
  1-D "rank" mesh. XLA lowers the sharded-in/replicated-or-resharded-
  out programs to all-reduce / all-gather / collective-permute /
  all-to-all over ICI/DCN. The controller's broadcast ResponseList
  guarantees all processes launch identical programs in identical
  order — the invariant XLA multi-controller execution requires.

Fusion note: a fused allreduce response becomes ONE program over the
concatenation of its flattened tensors (XLA's combiner plays the role
of the reference's fusion-buffer memcpy kernels,
``cuda/cuda_kernels.cu``); per-tensor average/prescale/postscale
factors are applied as a traced per-segment factor vector, so dynamic
loss scaling never recompiles.
"""

from __future__ import annotations

from functools import lru_cache
from typing import List, Tuple

import numpy as np

from horovod_tpu.common import basics
from horovod_tpu.common.ops_enum import ReduceOp


def invalidate_world() -> None:
    """Drop every cached mesh and jitted program. Called when the
    process-spanning XLA runtime is torn down (elastic re-formation,
    ``Runtime._teardown_jax_distributed``): the cached programs bake in
    the old world's mesh/devices, which no longer exist after
    ``clear_backends``."""
    for fn in (_rank_mesh, _scale_jit, _allreduce_prog, _allgather_prog,
               _broadcast_prog, _alltoall_prog, _reducescatter_prog):
        fn.cache_clear()


def zeros_state(name: str, op: int, n_elems: int, dtype_id: int,
                reduce_op: int):
    """Placeholder in-flight state for a rank with no local tensor (it
    joined): a zeros contribution so the SPMD program still launches
    here with collectives identical to every other process (reference
    feeds zeros for joined ranks, ``operations.cc:260``)."""
    import jax.numpy as jnp
    from horovod_tpu.runtime import _InFlight

    st = _InFlight()
    st.name = name
    st.op = op
    st.orig_kind = "jax"
    st.reduce_op = ReduceOp(reduce_op)
    st.input_dev = jnp.zeros((int(n_elems),), basics.np_dtype(dtype_id))
    return st


def _scale_factor(st, size: int) -> float:
    f = st.prescale * st.postscale
    if st.reduce_op == ReduceOp.AVERAGE:
        f /= size
    return f


def _check_scalable(dtype, factor: float) -> None:
    dt = np.dtype(dtype)
    is_float = dt.kind == "f" or dt.name in ("bfloat16", "float8_e4m3",
                                             "float8_e5m2")
    if factor != 1.0 and not is_float:
        raise TypeError(
            f"scaling (average/prescale/postscale) is not defined for "
            f"integer dtype {dt.name}; use op=Sum or cast to a float dtype "
            "first")


def _apply_factor(y, factor):
    """Shared dtype-promotion policy for the traced scale factor: low
    precision upcasts to f32 for the multiply; f32 and wider multiply
    in their own dtype (the factor is passed as float64 so f64 inputs
    keep full precision under x64 mode)."""
    import jax.numpy as jnp

    if jnp.dtype(y.dtype).itemsize < 4:
        return (y.astype(jnp.float32) * factor.astype(jnp.float32)).astype(
            y.dtype)
    return y * factor.astype(y.dtype)


def _factor_scalar(f: float) -> np.float64:
    """Factor as a numpy scalar for the jitted programs. float64 so f64
    tensors don't lose precision; under default (x64-disabled) JAX this
    traces as f32, which is all the device path supports anyway."""
    return np.float64(f)


@lru_cache(maxsize=None)
def _scale_jit():
    """Jitted x*f with the factor TRACED (one compile per dtype/shape,
    not per factor value — dynamic loss scaling changes the factor
    every few steps). Callers must reject integer dtypes first
    (:func:`_check_scalable`)."""
    import jax

    return jax.jit(_apply_factor)


_OP_SPAN = {basics.OP_ALLREDUCE: "allreduce",
            basics.OP_ALLGATHER: "allgather",
            basics.OP_BROADCAST: "broadcast",
            basics.OP_ALLTOALL: "alltoall",
            basics.OP_REDUCESCATTER: "reducescatter"}


def execute(op: int, states, sizes: List[int], size: int, rank: int):
    """Execute one CALLBACK response. Wrapped in a ``jax.profiler``
    span so device traces show the collective under the same phase
    names as the host timeline (the reference's NVTX ranges,
    ``common/nvtx_op_range.cc``; here the device story is
    ``jax.profiler.trace``/TensorBoard)."""
    import jax.profiler

    name = states[0].name if states else "?"
    with jax.profiler.TraceAnnotation(
            f"hvd:{_OP_SPAN.get(op, op)}:{name}"):
        return _execute(op, states, sizes, size, rank)


def _execute(op: int, states, sizes: List[int], size: int, rank: int):
    if size == 1:
        outs = []
        for st in states:
            x = st.input_dev
            if op in (basics.OP_ALLREDUCE, basics.OP_REDUCESCATTER):
                f = _scale_factor(st, 1)
                if f != 1.0:
                    _check_scalable(x.dtype, f)
                    x = _scale_jit()(x, _factor_scalar(f))
            # allgather/broadcast/alltoall over 1 rank: identity
            # (alltoall recvsplits are filled by the native core).
            outs.append(x)
        return outs
    if op == basics.OP_ALLREDUCE:
        return _dist_allreduce(states, size)
    if op == basics.OP_ALLGATHER:
        # Fused responses carry per-tensor blocks of `size` row counts.
        return [_dist_allgather(st, tuple(sizes[t * size:(t + 1) * size]),
                                size)
                for t, st in enumerate(states)]
    if op == basics.OP_BROADCAST:
        return [_dist_broadcast(states[0], size)]
    if op == basics.OP_ALLTOALL:
        return [_dist_alltoall(states[0], tuple(sizes), size, rank)]
    if op == basics.OP_REDUCESCATTER:
        return [_dist_reducescatter(states[0], tuple(sizes), size, rank)]
    raise NotImplementedError(f"unknown CALLBACK op {op}")


# ---------------------------------------------------------------------------
# distributed programs (multi-process, one device per process)
# ---------------------------------------------------------------------------

@lru_cache(maxsize=None)
def _rank_mesh():
    """1-D mesh over all processes' devices, axis "rank". Requires one
    device per process so the axis length equals the world size."""
    import jax
    from jax.sharding import Mesh

    if jax.local_device_count() != 1:
        raise NotImplementedError(
            "eager distributed XLA execution requires one device per "
            "process (the Horovod process model). On multi-chip TPU "
            "hosts launch with `horovodrun --tpu`, which carves each "
            "host into single-chip processes (runner/tpu.py); or use "
            "the SPMD functional API (horovod_tpu.ops) for multi-device "
            "processes")
    return Mesh(np.asarray(jax.devices(), dtype=object), ("rank",))


def _make_global(local, size: int):
    """Assemble the (size, ...) global array whose rank-th row is this
    process's ``local`` (shape ``local.shape``), sharded over "rank"."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _rank_mesh()
    sharding = NamedSharding(mesh, P("rank"))
    dev = mesh.local_mesh.devices.flat[0]
    local = jax.device_put(local[None], dev)
    return jax.make_array_from_single_device_arrays(
        (size,) + tuple(local.shape[1:]), sharding, [local])


def _local(arr):
    """This process's addressable piece of a global array (the full
    value for replicated outputs, the local shard otherwise)."""
    return arr.addressable_data(0)


def _pad_rows(x, rows: int):
    import jax.numpy as jnp

    if x.shape[0] == rows:
        return x
    pad = [(0, rows - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
    return jnp.pad(x, pad)


def _reduce_over_ranks(op: ReduceOp, arr):
    """Shared rank-axis reduction for allreduce / reducescatter
    programs (axis 0 is the mesh-sharded rank axis)."""
    import jax.numpy as jnp

    if op in (ReduceOp.AVERAGE, ReduceOp.SUM):
        return jnp.sum(arr, axis=0)
    if op == ReduceOp.MIN:
        return jnp.min(arr, axis=0)
    if op == ReduceOp.MAX:
        return jnp.max(arr, axis=0)
    if op == ReduceOp.PRODUCT:
        return jnp.prod(arr, axis=0)
    if op == ReduceOp.ADASUM:
        raise ValueError("adasum reducescatter is not defined; use allreduce")
    raise ValueError(f"unknown reduce op {op!r}")


def _adasum_tree(arr, spans: Tuple[int, ...]):
    """Adasum over the rank axis of a (size, total) batch: zero-pad
    ranks to a power of two (a zero operand passes its partner through
    unchanged) and fold consecutive pairs — the same binary operator
    tree as the native core's distance-doubling (ops.cc
    AdasumAllreduce), with dot/norm coefficients PER fused segment
    (per-tensor weighting, reference adasum.h:101-122)."""
    import jax.numpy as jnp

    acc = jnp.promote_types(arr.dtype, jnp.float32)
    offs = np.concatenate([[0], np.cumsum(spans)])
    m = arr.shape[0]
    pow2 = 1 << max(0, int(m - 1).bit_length())
    if pow2 != m:
        arr = jnp.pad(arr, [(0, pow2 - m)] + [(0, 0)] * (arr.ndim - 1))
    x = arr.astype(acc)
    while x.shape[0] > 1:
        a, b = x[0::2], x[1::2]
        segs = []
        for i in range(len(spans)):
            sa, sb = a[:, offs[i]:offs[i + 1]], b[:, offs[i]:offs[i + 1]]
            dot = jnp.sum(sa * sb, axis=1, keepdims=True)
            na2 = jnp.sum(sa * sa, axis=1, keepdims=True)
            nb2 = jnp.sum(sb * sb, axis=1, keepdims=True)
            ac = jnp.where(na2 > 0,
                           1.0 - dot / (2.0 * jnp.where(na2 > 0, na2, 1.0)),
                           1.0)
            bc = jnp.where(nb2 > 0,
                           1.0 - dot / (2.0 * jnp.where(nb2 > 0, nb2, 1.0)),
                           1.0)
            segs.append(ac * sa + bc * sb)
        x = jnp.concatenate(segs, axis=1)
    return x[0].astype(arr.dtype)


def _op_class(op: ReduceOp) -> ReduceOp:
    """Program-identity class: AVERAGE folds into SUM (averaging rides
    the traced factor vector), mirroring the controller's fusion classes
    so every rank — including joined ranks that only know the
    response-level op — derives the identical program key. ADASUM stays
    distinct: its program body differs."""
    if op == ReduceOp.AVERAGE:
        return ReduceOp.SUM
    return op


@lru_cache(maxsize=None)
def _allreduce_prog(op: ReduceOp, spans: Tuple[int, ...], inexact: bool):
    """One program per (reduce class, segment layout, dtype kind):
    reduce the (size, total) batch over ranks, then apply the traced
    per-segment factor vector. Program identity must NOT depend on
    factor values — a joined rank synthesizes factor 1.0 and still has
    to trace the identical HLO — so the multiply is always present for
    inexact dtypes (the factors are jit arguments)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _rank_mesh()
    repl = NamedSharding(mesh, P())
    repeats = np.asarray(spans)

    def fn(arr, factors):
        if op == ReduceOp.ADASUM:
            y = _adasum_tree(arr, spans)
        else:
            y = _reduce_over_ranks(op, arr)
        if inexact:
            y = _apply_factor(y, jnp.repeat(factors, repeats,
                                            total_repeat_length=int(
                                                repeats.sum())))
        return y

    return jax.jit(fn, out_shardings=repl)


def _dist_allreduce(states, size: int):
    """One fused program over the concatenation of the batch's
    flattened tensors (all share a dtype — the controller's fusion
    criterion)."""
    import jax.numpy as jnp

    spans = tuple(int(np.prod(st.input_dev.shape, dtype=np.int64))
                  for st in states)
    factors = [_scale_factor(st, size) for st in states]
    for st, f in zip(states, factors):
        if f != 1.0:
            _check_scalable(st.input_dev.dtype, f)
    local = jnp.concatenate(
        [jnp.ravel(jnp.asarray(st.input_dev)) for st in states])
    arr = _make_global(local, size)
    inexact = np.dtype(local.dtype).kind == "f" or \
        np.dtype(local.dtype).name == "bfloat16"
    if states[0].reduce_op == ReduceOp.ADASUM and not inexact:
        raise TypeError(
            f"adasum requires a float dtype, got {local.dtype}")
    # numpy f64 in, silent downcast to f32 unless x64 is enabled — same
    # policy as _factor_scalar.
    y = _allreduce_prog(_op_class(states[0].reduce_op), spans, inexact)(
        arr, jnp.asarray(np.asarray(factors, dtype=np.float64)))
    y = _local(y)
    outs, off = [], 0
    for st, span in zip(states, spans):
        outs.append(y[off:off + span].reshape(st.input_dev.shape))
        off += span
    return outs


@lru_cache(maxsize=None)
def _allgather_prog(sizes: Tuple[int, ...], rest: Tuple[int, ...]):
    """Gather uneven-row tensors: ranks pad to the max row count, the
    program slices out the real rows and concatenates (XLA lowers the
    replicated output to an all-gather)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _rank_mesh()
    repl = NamedSharding(mesh, P())

    def fn(arr):  # (size, max_rows, *rest)
        return jnp.concatenate(
            [arr[r, :sizes[r]] for r in range(len(sizes))], axis=0)

    return jax.jit(fn, out_shardings=repl)


def _dist_allgather(st, sizes: Tuple[int, ...], size: int):
    import jax.numpy as jnp

    x = jnp.asarray(st.input_dev)
    arr = _make_global(_pad_rows(x, max(sizes)), size)
    return _local(_allgather_prog(sizes, tuple(x.shape[1:]))(arr))


@lru_cache(maxsize=None)
def _broadcast_prog(root: int):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _rank_mesh()
    repl = NamedSharding(mesh, P())
    return jax.jit(lambda arr: arr[root], out_shardings=repl)


def _dist_broadcast(st, size: int):
    import jax.numpy as jnp

    arr = _make_global(jnp.asarray(st.input_dev), size)
    return _local(_broadcast_prog(int(st.root_rank))(arr))


@lru_cache(maxsize=None)
def _alltoall_prog(matrix: Tuple[int, ...], size: int,
                   max_send: int, rest: Tuple[int, ...]):
    """Uneven all-to-all from the full splits matrix
    (``matrix[r*size+k]`` = rows rank r RECEIVES from rank k, i.e.
    rank k's send chunk to r). Every rank pads its send buffer to
    ``max_send`` rows; the program re-slices chunks into each
    receiver's (padded) output row, sharded back over ranks."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _rank_mesh()
    out_sh = NamedSharding(mesh, P("rank"))

    def send_chunk(k: int, r: int) -> Tuple[int, int]:
        # Rows k sends to r start after k's chunks for ranks < r.
        start = sum(matrix[q * size + k] for q in range(r))
        return start, matrix[r * size + k]

    recv_rows = [sum(matrix[r * size + k] for k in range(size))
                 for r in range(size)]
    max_recv = max(recv_rows + [1])

    def fn(arr):  # (size, max_send, *rest)
        rows = []
        for r in range(size):
            chunks = []
            for k in range(size):
                start, n = send_chunk(k, r)
                if n:
                    chunks.append(arr[k, start:start + n])
            row = (jnp.concatenate(chunks, axis=0) if chunks
                   else jnp.zeros((0,) + rest, arr.dtype))
            rows.append(_pad_rows(row, max_recv))
        return jnp.stack(rows)

    return jax.jit(fn, out_shardings=out_sh)


def _dist_alltoall(st, matrix: Tuple[int, ...], size: int, rank: int):
    import jax.numpy as jnp

    x = jnp.asarray(st.input_dev)
    # Every rank must pad to the same static max; send totals are the
    # column sums of the matrix.
    send_totals = [sum(matrix[r * size + k] for r in range(size))
                   for k in range(size)]
    max_send = max(send_totals + [1])
    arr = _make_global(_pad_rows(x, max_send), size)
    out = _alltoall_prog(matrix, size, max_send, tuple(x.shape[1:]))(arr)
    my_rows = sum(matrix[rank * size + k] for k in range(size))
    return _local(out)[0][:my_rows]


@lru_cache(maxsize=None)
def _reducescatter_prog(op: ReduceOp, sizes: Tuple[int, ...],
                        inexact: bool):
    """Reduce over ranks, then scatter dim-0 shards back (uneven shards
    via per-rank slices padded to the max; output sharded over ranks so
    XLA can lower to reduce-scatter). Factor traced, same identity
    policy as :func:`_allreduce_prog`."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _rank_mesh()
    out_sh = NamedSharding(mesh, P("rank"))
    offs = np.concatenate([[0], np.cumsum(sizes)]).tolist()
    max_shard = max(sizes)

    def fn(arr, factor):  # (size, n0, *rest)
        y = _reduce_over_ranks(op, arr)
        if inexact:
            y = _apply_factor(y, factor)
        return jnp.stack([
            _pad_rows(y[offs[r]:offs[r + 1]], max_shard)
            for r in range(len(sizes))])

    return jax.jit(fn, out_shardings=out_sh)


def _dist_reducescatter(st, sizes: Tuple[int, ...], size: int, rank: int):
    import jax.numpy as jnp

    x = jnp.asarray(st.input_dev)
    f = _scale_factor(st, size)
    if f != 1.0:
        _check_scalable(x.dtype, f)
    inexact = np.dtype(x.dtype).kind == "f" or \
        np.dtype(x.dtype).name == "bfloat16"
    arr = _make_global(x, size)
    out = _reducescatter_prog(_op_class(st.reduce_op), sizes, inexact)(
        arr, _factor_scalar(f))
    return _local(out)[0][:sizes[rank]]
