"""XLA executor for eager CALLBACK-mode responses.

The NCCL-ops analog (reference ``horovod/common/ops/nccl_operations.cc``):
the native controller decides *when* and *in what order* a fused batch
runs; this module decides *how* — by launching a jitted XLA program.
Grouped entries become one multi-operand program (XLA's combiner plays
the role of the fusion-buffer memcpy kernels, reference
``cuda/cuda_kernels.cu``).

Process topologies:

* size == 1: collectives over ranks degenerate to (scaled) identity —
  jitted so dtype/scale semantics match the distributed path exactly.
* multi-process under ``jax.distributed`` with one device per process:
  ``psum``-style programs over a process-spanning mesh move bytes over
  ICI/DCN. The controller guarantees all processes launch the same
  program in the same order (the requirement XLA multi-controller
  imposes, and exactly what Horovod's coordinator was built to
  provide).
* multi-device-per-process pods route through the SPMD tier
  (:mod:`horovod_tpu.ops.collectives`) instead; the eager tier raises
  until the pod launcher lands.
"""

from __future__ import annotations

from functools import lru_cache
from typing import List

import numpy as np

from horovod_tpu.common import basics
from horovod_tpu.common.ops_enum import ReduceOp


def _scale_factor(st, size: int) -> float:
    f = st.prescale * st.postscale
    if st.reduce_op == ReduceOp.AVERAGE:
        f /= size
    return f


@lru_cache(maxsize=None)
def _scale_jit():
    import jax
    from functools import partial
    from horovod_tpu.ops.collectives import _scale

    return partial(jax.jit, static_argnums=(1,))(_scale)


def execute(op: int, states, sizes: List[int], size: int, rank: int):
    if size == 1:
        outs = []
        for st in states:
            x = st.input_dev
            if op in (basics.OP_ALLREDUCE, basics.OP_REDUCESCATTER):
                f = _scale_factor(st, 1)
                if f != 1.0:
                    x = _scale_jit()(x, f)
            # allgather/broadcast/alltoall over 1 rank: identity
            # (alltoall recvsplits are filled by the native core).
            outs.append(x)
        return outs
    if op == basics.OP_ALLREDUCE:
        return _distributed_allreduce(states, size)
    raise NotImplementedError(
        f"multi-process XLA execution for op {op} lands with the pod "
        "launcher; host-staged execution handles this case today")


@lru_cache(maxsize=None)
def _rank_mesh():
    """1-D mesh over all processes' devices, axis "rank". Requires one
    device per process so the axis length equals the world size."""
    import jax
    from jax.sharding import Mesh

    if jax.local_device_count() != 1:
        raise NotImplementedError(
            "eager distributed XLA allreduce currently requires one device "
            "per process (the Horovod process model); use the SPMD "
            "functional API (horovod_tpu.ops) for multi-device processes")
    return Mesh(np.asarray(jax.devices(), dtype=object), ("rank",))


@lru_cache(maxsize=None)
def _reduce_jit(op: ReduceOp, factor: float):
    import jax
    import jax.numpy as jnp
    from horovod_tpu.ops.collectives import _scale

    def fn(arr):
        if op in (ReduceOp.AVERAGE, ReduceOp.SUM, ReduceOp.ADASUM):
            y = jnp.sum(arr, axis=0)
        elif op == ReduceOp.MIN:
            y = jnp.min(arr, axis=0)
        elif op == ReduceOp.MAX:
            y = jnp.max(arr, axis=0)
        elif op == ReduceOp.PRODUCT:
            y = jnp.prod(arr, axis=0)
        else:
            raise ValueError(f"unknown reduce op {op!r}")
        return _scale(y, factor) if factor != 1.0 else y

    return jax.jit(fn)


def _distributed_allreduce(states, size: int):
    """Reduce each entry across processes: build a global batch-of-
    shards array (leading axis = process), reduce over it, read back
    the (replicated) result. XLA lowers the sum-over-sharded-axis to an
    all-reduce over ICI/DCN."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _rank_mesh()
    sharding = NamedSharding(mesh, P("rank"))
    local_device = mesh.local_mesh.devices.flat[0]

    outs = []
    for st in states:
        x = st.input_dev
        local = jax.device_put(jnp.asarray(x)[None], local_device)
        arr = jax.make_array_from_single_device_arrays(
            (size,) + tuple(x.shape), sharding, [local])
        outs.append(_reduce_jit(st.reduce_op, _scale_factor(st, size))(arr))
    return outs
