"""XLA executor for eager CALLBACK-mode responses.

The NCCL-ops analog (reference ``horovod/common/ops/nccl_operations.cc``):
the native controller decides *when* and *in what order* a fused batch
runs; this module decides *how* — by launching a jitted XLA program.
Grouped entries become one multi-operand program (XLA's combiner plays
the role of the fusion-buffer memcpy kernels, reference
``cuda/cuda_kernels.cu``).

Process topologies:

* size == 1: collectives over ranks degenerate to (scaled) identity —
  jitted so dtype/scale semantics match the distributed path exactly.
* multi-process under ``jax.distributed`` with one device per process:
  ``psum``-style programs over a process-spanning mesh move bytes over
  ICI/DCN. The controller guarantees all processes launch the same
  program in the same order (the requirement XLA multi-controller
  imposes, and exactly what Horovod's coordinator was built to
  provide).
* multi-device-per-process pods route through the SPMD tier
  (:mod:`horovod_tpu.ops.collectives`) instead; the eager tier raises
  until the pod launcher lands.
"""

from __future__ import annotations

from functools import lru_cache
from typing import List

import numpy as np

from horovod_tpu.common import basics
from horovod_tpu.common.ops_enum import ReduceOp


def zeros_state(name: str, op: int, n_elems: int, dtype_id: int,
                reduce_op: int):
    """Placeholder in-flight state for a rank with no local tensor (it
    joined): a zeros contribution so the SPMD program still launches
    here with collectives identical to every other process (reference
    feeds zeros for joined ranks, ``operations.cc:260``)."""
    import jax.numpy as jnp
    from horovod_tpu.runtime import _InFlight

    st = _InFlight()
    st.name = name
    st.op = op
    st.orig_kind = "jax"
    st.reduce_op = ReduceOp(reduce_op)
    st.input_dev = jnp.zeros((int(n_elems),), basics.np_dtype(dtype_id))
    return st


def _scale_factor(st, size: int) -> float:
    f = st.prescale * st.postscale
    if st.reduce_op == ReduceOp.AVERAGE:
        f /= size
    return f


def _check_scalable(dtype, factor: float) -> None:
    dt = np.dtype(dtype)
    is_float = dt.kind == "f" or dt.name in ("bfloat16", "float8_e4m3",
                                             "float8_e5m2")
    if factor != 1.0 and not is_float:
        raise TypeError(
            f"scaling (average/prescale/postscale) is not defined for "
            f"integer dtype {dt.name}; use op=Sum or cast to a float dtype "
            "first")


def _apply_factor(y, factor):
    """Shared dtype-promotion policy for the traced scale factor: low
    precision upcasts to f32 for the multiply; f32 and wider multiply
    in their own dtype (the factor is passed as float64 so f64 inputs
    keep full precision under x64 mode)."""
    import jax.numpy as jnp

    if jnp.dtype(y.dtype).itemsize < 4:
        return (y.astype(jnp.float32) * factor.astype(jnp.float32)).astype(
            y.dtype)
    return y * factor.astype(y.dtype)


def _factor_scalar(f: float) -> np.float64:
    """Factor as a numpy scalar for the jitted programs. float64 so f64
    tensors don't lose precision; under default (x64-disabled) JAX this
    traces as f32, which is all the device path supports anyway."""
    return np.float64(f)


@lru_cache(maxsize=None)
def _scale_jit():
    """Jitted x*f with the factor TRACED (one compile per dtype/shape,
    not per factor value — dynamic loss scaling changes the factor
    every few steps). Callers must reject integer dtypes first
    (:func:`_check_scalable`)."""
    import jax

    return jax.jit(_apply_factor)


def execute(op: int, states, sizes: List[int], size: int, rank: int):
    if size == 1:
        outs = []
        for st in states:
            x = st.input_dev
            if op in (basics.OP_ALLREDUCE, basics.OP_REDUCESCATTER):
                f = _scale_factor(st, 1)
                if f != 1.0:
                    _check_scalable(x.dtype, f)
                    x = _scale_jit()(x, _factor_scalar(f))
            # allgather/broadcast/alltoall over 1 rank: identity
            # (alltoall recvsplits are filled by the native core).
            outs.append(x)
        return outs
    if op == basics.OP_ALLREDUCE:
        return _distributed_allreduce(states, size)
    raise NotImplementedError(
        f"multi-process XLA execution for op {op} lands with the pod "
        "launcher; host-staged execution handles this case today")


@lru_cache(maxsize=None)
def _rank_mesh():
    """1-D mesh over all processes' devices, axis "rank". Requires one
    device per process so the axis length equals the world size."""
    import jax
    from jax.sharding import Mesh

    if jax.local_device_count() != 1:
        raise NotImplementedError(
            "eager distributed XLA allreduce currently requires one device "
            "per process (the Horovod process model); use the SPMD "
            "functional API (horovod_tpu.ops) for multi-device processes")
    return Mesh(np.asarray(jax.devices(), dtype=object), ("rank",))


@lru_cache(maxsize=None)
def _reduce_jit(op: ReduceOp):
    """One compiled program per (reduce op, dtype, elem count) — the
    scale factor is a TRACED scalar so dynamic loss scaling never
    recompiles. Operates on flattened tensors: program identity across
    processes then depends only on element count, which joined ranks
    know from the response metadata even without a local tensor."""
    import jax
    import jax.numpy as jnp

    def fn(arr, factor):
        if op in (ReduceOp.AVERAGE, ReduceOp.SUM, ReduceOp.ADASUM):
            y = jnp.sum(arr, axis=0)
        elif op == ReduceOp.MIN:
            y = jnp.min(arr, axis=0)
        elif op == ReduceOp.MAX:
            y = jnp.max(arr, axis=0)
        elif op == ReduceOp.PRODUCT:
            y = jnp.prod(arr, axis=0)
        else:
            raise ValueError(f"unknown reduce op {op!r}")
        if jnp.issubdtype(y.dtype, jnp.inexact):
            y = _apply_factor(y, factor)
        return y

    return jax.jit(fn)


def _reduce_factor(st, size: int) -> np.float64:
    """Factor for the distributed reduce; rejects scaled integer inputs
    loudly rather than truncating the factor to 0."""
    f = _scale_factor(st, size)
    _check_scalable(st.input_dev.dtype, f)
    return _factor_scalar(f)


def _distributed_allreduce(states, size: int):
    """Reduce each entry across processes: build a global batch-of-
    shards array (leading axis = process) from the FLATTENED local
    tensor, reduce over it, reshape back. XLA lowers the
    sum-over-sharded-axis to an all-reduce over ICI/DCN."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _rank_mesh()
    sharding = NamedSharding(mesh, P("rank"))
    local_device = mesh.local_mesh.devices.flat[0]

    outs = []
    for st in states:
        x = st.input_dev
        shape = tuple(x.shape)
        local = jax.device_put(jnp.ravel(jnp.asarray(x))[None], local_device)
        arr = jax.make_array_from_single_device_arrays(
            (size, local.shape[1]), sharding, [local])
        y = _reduce_jit(st.reduce_op)(arr, _reduce_factor(st, size))
        outs.append(y.reshape(shape))
    return outs
