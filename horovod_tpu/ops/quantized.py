"""Quantized in-jit mesh collectives (EQuARX, arXiv:2506.17615).

PR 3 compressed the host TCP ring; this module compresses the plane the
models actually train on — the in-``jit`` collectives over NamedSharding
meshes. Pure ``jnp`` (Pallas hard-aborts on this container's XLA-CPU),
callable only under ``shard_map`` with the named axis fully manual.

Codecs, mirroring ``native/src/codec.cc`` exactly:

* **bf16 / fp16** — cast the wire representation down, reduce in f32.
* **int8** — blockwise-scaled: each :data:`INT8_BLOCK_ELEMS`-element
  block carries a ``absmax/127`` f32 scale; values quantize with
  round-to-nearest-even (``jnp.round`` lowers to
  ``lax.round(ROUND_TO_NEAREST_EVEN)``, the same RNE contract as the
  native plane's branchless magic-constant trick in ``codec.cc`` —
  bit-identical over the ±127 range) and clamp to ``[-127, 127]``.

The allreduce is the MLPerf-TPU reduce-scatter + all-gather
decomposition (arXiv:1909.09756) with both hops shipping narrow bytes:

1. quantize the local value, blockwise per destination shard;
2. reduce-scatter the narrow payload — expressed as ``lax.all_to_all``
   of the int8/bf16 bytes plus a local f32 fold, because a reduction
   collective cannot sum int8 encodings under per-rank scales (and the
   legacy XLA-CPU ``AllReducePromotion`` pass aborts on sub-f32
   ``psum_scatter`` operands); the wire bytes equal ``psum_scatter``'s;
3. **requantize** the reduced shard;
4. ``lax.all_gather`` the narrow bytes and dequantize.

Determinism contract (same as ``HostAccumulate``): the fold is a fixed
``sum(axis=0)`` over peer order and every decode is a *multiply* by the
scale (``q * s``, never ``q / inv``) — a constant division gets
algebraically rewritten under jit and breaks the jit/no-jit bitwise
identity the tests pin.

Error feedback (int8): the rank-local residual telescopes the rounding
error across steps exactly like the host plane's EF slabs. Both
quantization points are compensated: hop 1's encode error everywhere,
and hop 2's requantize error on the shard this rank owns (it is the
rank that performed that encode), so the summed decoded contributions
reconstruct the collective's actual output and the time-average of the
quantized mean converges to the true mean on a fixed gradient (the
telescoping identity pinned in tests/test_quantized.py).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.common.ops_enum import Average, ReduceOp, Sum

# Elements per int8 quantization block — pinned to the native plane's
# kInt8BlockElems (native/include/hvd/codec.h) by tests/test_wire_abi.py
# and the tools/lint wire-codec-pins rule, so one knob means one block
# geometry on both planes.
INT8_BLOCK_ELEMS = 256

#: In-jit codec names (the `in_jit_codec` values compression.py maps to).
CODECS = ("none", "bf16", "fp16", "int8")

_CAST_WIRE = {"bf16": jnp.bfloat16, "fp16": jnp.float16}


# ---------------------------------------------------------------------------
# Blockwise int8 codec (pure jnp, shapes static)
# ---------------------------------------------------------------------------

def int8_blocks(n: int) -> int:
    """ceil-div block count for ``n`` elements (codec.h Int8Blocks)."""
    return -(-n // INT8_BLOCK_ELEMS)


def blockwise_int8_encode(x):
    """Quantize ``x`` [..., C] blockwise along the last axis.

    Returns ``(q, scales)``: ``q`` int8 [..., NB*B] (C zero-padded up to
    whole blocks — pad lanes quantize to exactly 0 and never perturb a
    block's absmax), ``scales`` f32 [..., NB] with ``absmax/127`` per
    block (0 for an all-zero block, matching codec.cc).
    """
    x = x.astype(jnp.float32)
    c = x.shape[-1]
    nb = int8_blocks(c)
    pad = nb * INT8_BLOCK_ELEMS - c
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    v = x.reshape(x.shape[:-1] + (nb, INT8_BLOCK_ELEMS))
    absmax = jnp.max(jnp.abs(v), axis=-1)
    scales = absmax * jnp.float32(1.0 / 127.0)
    inv = jnp.where(scales > 0, 1.0 / scales, 0.0)
    q = jnp.clip(jnp.round(v * inv[..., None]), -127, 127).astype(jnp.int8)
    return q.reshape(x.shape[:-1] + (nb * INT8_BLOCK_ELEMS,)), scales


def blockwise_int8_decode(q, scales, c: int):
    """Dequantize ``(q, scales)`` back to f32 [..., c].

    Decode is ``q * scale`` — the native plane's exact arithmetic
    (Int8DecodeBlocks) and the jit-stable spelling (see module doc).
    """
    nb = scales.shape[-1]
    v = q.astype(jnp.float32).reshape(q.shape[:-1] + (nb, INT8_BLOCK_ELEMS))
    out = (v * scales[..., None]).reshape(q.shape)
    return out[..., :c]


# ---------------------------------------------------------------------------
# The quantized allreduce
# ---------------------------------------------------------------------------

def _axis_size(axis_name) -> int:
    from horovod_tpu.common.jax_compat import axis_size
    return axis_size(axis_name)


def _check_codec(codec: str):
    if codec not in CODECS:
        raise ValueError(f"unknown in-jit codec {codec!r}; one of {CODECS}")


def _check_axis_name(axis_name, fn_name: str):
    """Up-front rejection of tuple/list axis names on the quantized
    paths: the all_to_all decomposition addresses ONE named axis, and a
    tuple that slipped through used to die deep inside the collective
    with an opaque XLA shape error. A clear ValueError at the API edge
    is the contract (reshape the mesh, or reduce axis-by-axis — which
    is exactly how the fsdp+dp train step composes its hops)."""
    if not isinstance(axis_name, str):
        raise ValueError(
            f"{fn_name} reduces over a single named mesh axis; got "
            f"{axis_name!r}. Reshape the mesh or reduce axis-by-axis "
            "(sequential single-axis hops are the supported spelling "
            "for multi-axis meshes).")


def _native_cast_hop_ok(native_hop) -> bool:
    """Whether the cast-codec reduce-scatter hop may lower as ONE
    sub-f32 ``lax.psum_scatter`` instead of all_to_all + f32 fold.
    ``native_hop`` None = probe (jax_compat), True/False = forced."""
    if native_hop is not None:
        return bool(native_hop)
    from horovod_tpu.common.jax_compat import supports_narrow_psum_scatter
    return supports_narrow_psum_scatter()


def quantized_allreduce(x, op: ReduceOp = Average, axis_name: str = "dp", *,
                        codec: str, residual: Optional[jax.Array] = None,
                        native_hop: Optional[bool] = None):
    """Allreduce ``x`` over ``axis_name`` with narrow bytes on both hops.

    Call under ``shard_map`` with ``axis_name`` manual. ``codec`` is one
    of :data:`CODECS`; ``"none"`` takes the exact pre-existing
    ``lax.psum`` path (bitwise identical to an uncompressed allreduce).
    ``residual`` (int8/bf16/fp16; optional) is this rank's error-feedback
    buffer, shaped and typed like ``x`` in f32 — when given, the value
    quantized is ``x + residual`` and the call returns
    ``(reduced, new_residual)``; without it the rounding error of this
    step is dropped (plain quantized) and only ``reduced`` returns.

    Only ``Sum``/``Average`` are compressible (MIN/MAX/PRODUCT have no
    meaningful quantized composition); other ops raise.
    """
    _check_codec(codec)
    if codec == "none":
        y = lax.psum(x, axis_name)
        if op == Average:
            y = y / _axis_size(axis_name)
        elif op != Sum:
            raise ValueError("quantized_allreduce supports Sum/Average")
        return (y, residual) if residual is not None else y
    if op not in (Sum, Average):
        raise ValueError(
            f"compression={codec!r} supports op=Sum/Average only, got {op!r}")
    _check_axis_name(axis_name, "quantized_allreduce")
    if not jnp.issubdtype(x.dtype, jnp.floating):
        raise TypeError(
            f"cannot quantize dtype {x.dtype}; compression applies to "
            "float gradients")

    p = _axis_size(axis_name)
    orig_shape, orig_dtype = x.shape, x.dtype
    n = x.size
    xf = x.astype(jnp.float32).reshape(-1)
    if residual is not None:
        xf = xf + residual.astype(jnp.float32).reshape(-1)
    n_per = -(-n // p)                     # elements per scattered shard
    if n_per * p != n:
        xf = jnp.pad(xf, (0, n_per * p - n))
    v = xf.reshape(p, n_per)               # row r -> shard owned by rank r

    if codec == "int8":
        q1, s1 = blockwise_int8_encode(v)          # [P, NB*B], [P, NB]
        qr = lax.all_to_all(q1, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
        sr = lax.all_to_all(s1, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
        y = blockwise_int8_decode(qr, sr, n_per).sum(axis=0)   # [n_per] f32
        q2, s2 = blockwise_int8_encode(y[None])    # [1, NB*B], [1, NB]
        gq = lax.all_gather(q2[0], axis_name, axis=0, tiled=False)
        gs = lax.all_gather(s2[0], axis_name, axis=0, tiled=False)
        z = blockwise_int8_decode(gq, gs, n_per)   # [P, n_per] f32
        if residual is not None:
            e1 = v - blockwise_int8_decode(q1, s1, n_per)
            e2 = y - blockwise_int8_decode(q2, s2, n_per)[0]
    else:
        wire = _CAST_WIRE[codec]
        w1 = v.astype(wire)
        if _native_cast_hop_ok(native_hop):
            # psum_scatter-native hop: the backend reduces the narrow
            # operand itself — one collective, same wire bytes as the
            # all_to_all spelling, summation in the wire dtype.
            y = lax.psum_scatter(w1, axis_name,
                                 scatter_dimension=0).astype(jnp.float32)
        else:
            wr = lax.all_to_all(w1, axis_name, split_axis=0, concat_axis=0,
                                tiled=True)
            y = wr.astype(jnp.float32).sum(axis=0)
        w2 = y.astype(wire)
        z = lax.all_gather(w2, axis_name, axis=0,
                           tiled=False).astype(jnp.float32)
        if residual is not None:
            e1 = v - w1.astype(jnp.float32)
            e2 = y - w2.astype(jnp.float32)

    if op == Average:
        z = z * jnp.float32(1.0 / p)
    out = z.reshape(-1)[:n].reshape(orig_shape).astype(orig_dtype)
    if residual is None:
        return out
    # EF update: hop-1 encode error everywhere; hop-2 requantize error
    # on this rank's own shard row (sum space — the averaging factor
    # never enters the residual; see module doc).
    own = (jnp.arange(p) == lax.axis_index(axis_name))[:, None]
    new_r = e1 + jnp.where(own, e2[None, :], 0.0)
    new_r = new_r.reshape(-1)[:n].reshape(orig_shape)
    return out, new_r


def quantized_reduce_scatter(x, op: ReduceOp = Sum,
                             axis_name: str = "fsdp", *, codec: str,
                             axis: int = 0,
                             residual: Optional[jax.Array] = None,
                             native_hop: Optional[bool] = None):
    """Reduce-scatter ``x`` over ``axis_name`` with the hop bytes
    narrowed by ``codec`` — the explicit, interceptable spelling of the
    GSPMD-inserted fsdp gradient reduce-scatter.

    Composition (same contract as hop 1 of the allreduce): quantize
    blockwise per destination shard → ``lax.all_to_all`` of the narrow
    payload (+f32 scales for int8) → fixed-order **multiply-only** f32
    fold; the wire bytes equal ``psum_scatter``'s. For the cast codecs
    the fold may lower as ONE sub-f32 ``lax.psum_scatter`` where the
    backend allows (``native_hop`` None = the jax_compat probe; legacy
    XLA-CPU aborts on sub-f32 reduce collectives, so the probe keeps it
    off there).

    ``x``'s dim ``axis`` must divide by the axis size; this rank
    returns its slice (``x.shape`` with that dim divided). ``"none"``
    folds the exact f32 values (bitwise the psum-then-slice result
    under the same fixed fold order). ``residual`` (f32, ``x``-shaped)
    is this rank's EF buffer for the single encode point; with it the
    call returns ``(shard, new_residual)``.
    """
    _check_codec(codec)
    _check_axis_name(axis_name, "quantized_reduce_scatter")
    if op not in (Sum, Average):
        raise ValueError(
            f"quantized_reduce_scatter supports op=Sum/Average, got {op!r}")
    if codec != "none" and not jnp.issubdtype(x.dtype, jnp.floating):
        raise TypeError(
            f"cannot quantize dtype {x.dtype}; compression applies to "
            "float gradients")
    p = _axis_size(axis_name)
    axis = axis % x.ndim
    if x.shape[axis] % p:
        raise ValueError(
            f"quantized_reduce_scatter: dim {axis} of shape {x.shape} "
            f"does not divide by the {axis_name!r} axis size {p}")
    orig_dtype = x.dtype
    moved = jnp.moveaxis(x, axis, 0)
    # Row r of `rows` is the contiguous slab destined for rank r.
    rows = moved.astype(jnp.float32).reshape(p, -1)
    if residual is not None and codec != "none":
        rows = rows + jnp.moveaxis(residual.astype(jnp.float32),
                                   axis, 0).reshape(p, -1)
    shard_shape = (moved.shape[0] // p,) + moved.shape[1:]

    if codec == "none":
        rr = lax.all_to_all(rows, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
        y = rr.sum(axis=0)
        e1 = None
    elif codec == "int8":
        q1, s1 = blockwise_int8_encode(rows)
        qr = lax.all_to_all(q1, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
        sr = lax.all_to_all(s1, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
        y = blockwise_int8_decode(qr, sr, rows.shape[-1]).sum(axis=0)
        if residual is not None:
            e1 = rows - blockwise_int8_decode(q1, s1, rows.shape[-1])
    else:
        wire = _CAST_WIRE[codec]
        w1 = rows.astype(wire)
        if _native_cast_hop_ok(native_hop):
            y = lax.psum_scatter(w1, axis_name,
                                 scatter_dimension=0).astype(jnp.float32)
        else:
            wr = lax.all_to_all(w1, axis_name, split_axis=0, concat_axis=0,
                                tiled=True)
            y = wr.astype(jnp.float32).sum(axis=0)
        if residual is not None:
            e1 = rows - w1.astype(jnp.float32)

    if op == Average:
        y = y * jnp.float32(1.0 / p)
    shard = jnp.moveaxis(y.reshape(shard_shape), 0, axis).astype(orig_dtype)
    if residual is None:
        return shard
    if e1 is None:                       # codec "none": nothing dropped
        return shard, residual
    # Encode error in SUM space (the Average factor never enters the
    # residual, same discipline as the allreduce's EF update).
    return shard, jnp.moveaxis(e1.reshape(moved.shape), 0, axis)


def quantized_allgather(x, axis_name: str = "dp", *, codec: str,
                        axis: int = 0):
    """All-gather ``x`` with the wire bytes narrowed by ``codec``
    (tiled, like :func:`horovod_tpu.ops.collectives.allgather`). The
    int8 form ships blockwise q+scales and dequantizes after the hop;
    lossy like the allreduce's hop 2. ``"none"`` is the exact plain
    gather."""
    _check_codec(codec)
    if codec == "none":
        return lax.all_gather(x, axis_name, axis=axis, tiled=True)
    _check_axis_name(axis_name, "quantized_allgather")
    if not jnp.issubdtype(x.dtype, jnp.floating):
        raise TypeError(f"cannot quantize dtype {x.dtype}")
    orig_dtype = x.dtype
    if codec in _CAST_WIRE:
        w = x.astype(_CAST_WIRE[codec])
        return lax.all_gather(w, axis_name, axis=axis,
                              tiled=True).astype(orig_dtype)
    moved = jnp.moveaxis(x, axis, -1)
    c = moved.shape[-1]
    q, s = blockwise_int8_encode(moved)
    gq = lax.all_gather(q, axis_name, axis=-1, tiled=True)
    gs = lax.all_gather(s, axis_name, axis=-1, tiled=True)
    p = gq.shape[-1] // q.shape[-1]
    gq = gq.reshape(gq.shape[:-1] + (p, q.shape[-1]))
    gs = gs.reshape(gs.shape[:-1] + (p, s.shape[-1]))
    out = blockwise_int8_decode(gq, gs, c)          # [..., P, c]
    out = out.reshape(moved.shape[:-1] + (p * c,))  # concat peers in order
    return jnp.moveaxis(out, -1, axis).astype(orig_dtype)


# ---------------------------------------------------------------------------
# The quantized alltoall (MoE dispatch/combine hop, ISSUE 18)
# ---------------------------------------------------------------------------

def _plain_alltoall(x, axis_name: str):
    return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)


def _alltoall_value(x, axis_name: str, codec: str):
    """Forward value of the quantized alltoall: each destination slab
    ``x[d]`` is flattened and encoded as ONE blockwise payload (same
    slab-flattening discipline as the allreduce's per-shard rows, so
    block utilization never depends on the trailing-dim geometry), the
    narrow bytes (+f32 scales for int8) ride ``lax.all_to_all``, and
    the received slabs decode back to ``x.dtype``."""
    if codec == "none":
        return _plain_alltoall(x, axis_name)
    shape, dtype = x.shape, x.dtype
    if codec in _CAST_WIRE:
        w = x.astype(_CAST_WIRE[codec])
        return _plain_alltoall(w, axis_name).astype(dtype)
    rows = x.astype(jnp.float32).reshape(shape[0], -1)
    q, s = blockwise_int8_encode(rows)
    qr = _plain_alltoall(q, axis_name)
    sr = _plain_alltoall(s, axis_name)
    out = blockwise_int8_decode(qr, sr, rows.shape[-1])
    return out.reshape(shape).astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _qa2a(x, axis_name: str, codec: str, bwd_codec: str):
    return _alltoall_value(x, axis_name, codec)


def _qa2a_fwd(x, axis_name, codec, bwd_codec):
    return _alltoall_value(x, axis_name, codec), None


def _qa2a_bwd(axis_name, codec, bwd_codec, _res, g):
    # The tiled (split=concat=0) alltoall is its own transpose: the
    # slab that went p->q routes back q->p under the identical op. The
    # cotangent rides the SAME narrow wire (bwd_codec), quantized the
    # straight-through way — the rounding of the forward hop never
    # enters the backward graph (jnp.round's zero derivative would
    # otherwise kill every gradient flowing through the dispatch).
    return (_alltoall_value(g, axis_name, bwd_codec),)


_qa2a.defvjp(_qa2a_fwd, _qa2a_bwd)


def quantized_alltoall(x, axis_name: str = "ep", *, codec: str,
                       bwd_codec: Optional[str] = None):
    """Alltoall ``x`` over ``axis_name`` with the wire narrowed by
    ``codec`` — the explicit MoE dispatch/combine hop (EQuARX applied
    to the one collective that dominates sparse-model step time).

    Call under ``shard_map`` with ``axis_name`` manual. ``x``'s leading
    dim must equal the axis size P; slab ``x[d]`` is delivered to rank
    ``d`` and the result's slab ``[s]`` came from rank ``s`` (tiled
    ``lax.all_to_all`` semantics, split/concat axis 0).

    ``codec`` is one of :data:`CODECS`; ``"none"`` is the exact plain
    ``lax.all_to_all`` — bitwise the uncompressed hop, native autodiff.
    The lossy codecs are differentiable with a straight-through custom
    VJP whose backward hop ships ``bwd_codec`` (default: same as
    ``codec``) in the reverse direction — both directions of the
    exchange stay narrow.
    """
    _check_codec(codec)
    bwd = codec if bwd_codec is None else bwd_codec
    _check_codec(bwd)
    if codec == "none" and bwd == "none":
        return _plain_alltoall(x, axis_name)
    _check_axis_name(axis_name, "quantized_alltoall")
    p = _axis_size(axis_name)
    if x.shape[0] != p:
        raise ValueError(
            f"quantized_alltoall: leading dim {x.shape[0]} must equal "
            f"the {axis_name!r} axis size {p} (one slab per peer)")
    if not jnp.issubdtype(x.dtype, jnp.floating):
        raise TypeError(
            f"cannot quantize dtype {x.dtype}; compression applies to "
            "float activations")
    return _qa2a(x, axis_name, codec, bwd)


def alltoall_wire_bytes(shape, codec: str, *, elem_bytes: int = 4) -> int:
    """Bytes one :func:`quantized_alltoall` of a ``shape``-shaped f32
    payload puts on the wire (all P slabs, scales included) — the
    static accounting behind bench.py's ``moe_dispatch_bytes_saved_pct``
    (int8 ships ~1/3.94 of the f32 bytes once a slab spans a few
    blocks; tiny slabs amortize worse because the last block pads)."""
    _check_codec(codec)
    n = math.prod(shape)
    if codec == "none":
        return n * elem_bytes
    if codec in _CAST_WIRE:
        return n * 2
    per_slab = math.prod(shape[1:])
    nb = int8_blocks(per_slab)
    return shape[0] * nb * (INT8_BLOCK_ELEMS + 4)
