"""Functional collectives — the in-``jit`` SPMD data plane.

This is the TPU-native replacement for the reference's op layer
(``horovod/common/ops/collective_operations.h:38-288`` and the NCCL
implementations in ``ops/nccl_operations.cc``): instead of enqueueing
tensors to a background thread that drives ``ncclAllReduce`` on a
private stream, collectives here are *traced into the user's XLA
program* (``lax.psum``/``all_gather``/``psum_scatter``/``all_to_all``/
``ppermute``) and lowered by XLA onto ICI. Fusion (reference
``fusion_buffer_manager.cc``) is unnecessary in this tier: XLA's
combiner pass batches small collectives, and multi-operand ``psum`` of
a whole gradient pytree is the "grouped allreduce" of
``operations.cc:943`` for free.

All functions take ``axis_name`` (one of the mesh axes, or a tuple of
axes to reduce over several at once) and must be called under
``shard_map``/``pjit`` with a bound mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.common.ops_enum import ReduceOp, Average, Sum

AxisName = Union[str, Sequence[str]]


def axis_rank(axis_name: AxisName = "dp"):
    """This shard's index along ``axis_name`` (cf. ``hvd.rank()``)."""
    return lax.axis_index(axis_name)


def axis_size(axis_name: AxisName = "dp") -> int:
    """Static size of the named axis (cf. ``hvd.size()``)."""
    from horovod_tpu.common.jax_compat import axis_size as _axis_size
    return _axis_size(axis_name)


def _scale(x, factor):
    if factor is None or factor == 1.0:
        return x
    if not jnp.issubdtype(x.dtype, jnp.inexact):
        raise TypeError(
            f"scaling (average/prescale/postscale) is not defined for integer "
            f"dtype {x.dtype}; use op=Sum or cast to a float dtype first")
    # Scale in f32 for low-precision inputs to avoid bf16 rounding of the
    # factor itself (reference scales in the fusion buffer with a fused
    # kernel, ops/cuda/cuda_kernels.cu; XLA fuses this multiply for free).
    if jnp.dtype(x.dtype).itemsize < 4:
        return (x.astype(jnp.float32) * factor).astype(x.dtype)
    return x * factor


def allreduce(x, op: ReduceOp = Average, axis_name: AxisName = "dp", *,
              prescale_factor: Optional[float] = None,
              postscale_factor: Optional[float] = None,
              compression=None):
    """Reduce ``x`` across ``axis_name`` on every shard.

    Reference semantics: ``horovod/common/operations.cc:914``
    ``EnqueueTensorAllreduce`` + pre/postscale (``operations.cc:955-970``).
    ``Average`` divides by the axis size after summation.

    ``compression`` (a ``hvd.Compression`` member; None/none =
    uncompressed, the exact pre-existing path) routes Sum/Average
    through the quantized reduce-scatter + all-gather in
    :mod:`horovod_tpu.ops.quantized` so the collective ships narrow
    bytes inside the XLA graph — the in-jit face of the same knob the
    eager TCP plane reads as a wire codec.
    """
    from horovod_tpu import compression as compression_lib
    codec = compression_lib.in_jit_codec(compression)
    if codec != "none":
        if (op in (ReduceOp.AVERAGE, ReduceOp.SUM)
                and isinstance(axis_name, str)):
            from horovod_tpu.ops.quantized import quantized_allreduce
            x = _scale(x, prescale_factor)
            y = quantized_allreduce(x, op=op, axis_name=axis_name,
                                    codec=codec)
            return _scale(y, postscale_factor)
        if codec == "int8":
            raise ValueError(
                f"compression=int8 supports op=Sum/Average over a single "
                f"named axis (got op={op!r}, axis {axis_name!r}); the "
                "cast codecs (bf16/fp16) wrap the other shapes")
        # Cast codecs wrap everything else the plain path supports
        # (Max/Min/Product/Adasum, tuple axes): cast to the wire dtype
        # around the uncompressed collective — the same fallback
        # contract as allreduce_gradients.
        c, ctx = compression.compress(x)
        y = allreduce(c, op, axis_name, prescale_factor=prescale_factor,
                      postscale_factor=postscale_factor)
        return compression.decompress(y, ctx)
    x = _scale(x, prescale_factor)
    if op == ReduceOp.ADASUM:
        from horovod_tpu.ops.adasum import adasum_allreduce
        y = adasum_allreduce(x, axis_name)
    elif op in (ReduceOp.AVERAGE, ReduceOp.SUM):
        y = lax.psum(x, axis_name)
        if op == ReduceOp.AVERAGE:
            y = _scale(y, 1.0 / axis_size(axis_name))
    elif op == ReduceOp.MIN:
        y = lax.pmin(x, axis_name)
    elif op == ReduceOp.MAX:
        y = lax.pmax(x, axis_name)
    elif op == ReduceOp.PRODUCT:
        # XLA has no product collective; gather then reduce locally. The
        # trailing pmax is a no-op on the (identical) per-shard results
        # that re-establishes the replicated value type for shard_map.
        g = lax.all_gather(x, axis_name)
        y = lax.pmax(jnp.prod(g, axis=0), axis_name)
    else:
        raise ValueError(f"unknown reduce op {op!r}")
    return _scale(y, postscale_factor)


def grouped_allreduce(xs, op: ReduceOp = Average, axis_name: AxisName = "dp", *,
                      prescale_factor: Optional[float] = None,
                      postscale_factor: Optional[float] = None,
                      compression=None):
    """Allreduce a pytree of tensors as one logical step.

    Reference: ``EnqueueTensorAllreduces`` (``operations.cc:943``) +
    ``GroupTable`` atomic completion (``common/group_table.h:31``). In
    XLA a multi-operand ``psum`` compiles to batched collectives over
    one fused buffer — the moral equivalent of the reference's fusion
    buffer without the explicit memcpy kernels.

    ``compression`` routes each leaf through the quantized path (see
    :func:`allreduce`); XLA's combiner still batches the per-leaf
    narrow collectives.
    """
    from horovod_tpu import compression as compression_lib
    if compression_lib.in_jit_codec(compression) != "none":
        return jax.tree.map(
            lambda t: allreduce(t, op, axis_name,
                                prescale_factor=prescale_factor,
                                postscale_factor=postscale_factor,
                                compression=compression), xs)
    if op == ReduceOp.ADASUM:
        from horovod_tpu.ops.adasum import adasum_allreduce
        xs = jax.tree.map(lambda l: _scale(l, prescale_factor), xs)
        reduced = adasum_allreduce(xs, axis_name)
        return jax.tree.map(lambda l: _scale(l, postscale_factor), reduced)
    if op in (ReduceOp.AVERAGE, ReduceOp.SUM):
        leaves, treedef = jax.tree.flatten(xs)
        leaves = [_scale(l, prescale_factor) for l in leaves]
        reduced = lax.psum(tuple(leaves), axis_name)
        if op == ReduceOp.AVERAGE:
            inv = 1.0 / axis_size(axis_name)
            reduced = [_scale(l, inv) for l in reduced]
        reduced = [_scale(l, postscale_factor) for l in reduced]
        return jax.tree.unflatten(treedef, reduced)
    return jax.tree.map(
        lambda t: allreduce(t, op, axis_name, prescale_factor=prescale_factor,
                            postscale_factor=postscale_factor), xs)


def allgather(x, axis_name: AxisName = "dp", axis: int = 0):
    """Concatenate each shard's ``x`` along ``axis`` (reference
    ``EnqueueTensorAllgather``, ``operations.cc:1055``; like Horovod,
    shards may differ in dim-``axis`` *only* — ragged sizes are handled
    by the eager tier, not in-jit where shapes are static)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


def broadcast(x, root_rank: int = 0, axis_name: AxisName = "dp"):
    """Every shard receives shard ``root_rank``'s value.

    Reference: ``EnqueueTensorBroadcast`` (``operations.cc:1091``).
    Implemented as masked ``psum`` — one ICI reduction, no gather blowup;
    XLA recognises the select+reduce idiom.
    """
    n = axis_size(axis_name)
    if not (0 <= root_rank < n):
        raise ValueError(f"root_rank {root_rank} out of range for axis "
                         f"{axis_name!r} of size {n}")
    idx = lax.axis_index(axis_name)
    if jnp.issubdtype(x.dtype, jnp.bool_):
        y = lax.psum(jnp.where(idx == root_rank, x, False).astype(jnp.int8),
                     axis_name)
        return y.astype(jnp.bool_)
    return lax.psum(jnp.where(idx == root_rank, x, jnp.zeros_like(x)), axis_name)


def alltoall(x, axis_name: AxisName = "dp", split_axis: int = 0,
             concat_axis: int = 0):
    """Scatter ``x`` along ``split_axis`` to the axis peers and gather
    their slices along ``concat_axis`` (reference
    ``EnqueueTensorAlltoall``, ``operations.cc:1131``; on TPU this is
    the Ulysses/MoE primitive and lowers to an ICI all-to-all)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def reducescatter(x, op: ReduceOp = Average, axis_name: AxisName = "dp",
                  scatter_axis: int = 0):
    """Sum across the axis, leaving each shard with its 1/N slice along
    ``scatter_axis``. The reference only reaches reduce-scatter inside
    hierarchical allreduce (``nccl_operations.cc:187-360``); on TPU it
    is first-class — the FSDP gradient path."""
    if op not in (ReduceOp.AVERAGE, ReduceOp.SUM):
        raise ValueError("reducescatter supports SUM/AVERAGE")
    y = lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis,
                         tiled=True)
    if op == ReduceOp.AVERAGE:
        y = _scale(y, 1.0 / axis_size(axis_name))
    return y


def ring_permute(x, axis_name: AxisName = "sp", shift: int = 1):
    """Send ``x`` to the neighbor ``shift`` hops along the axis ring
    (``lax.ppermute``) — the building block of ring attention and the
    TPU analog of neighbor exchanges the reference never needed
    (its DP-only model has no ring pipelines)."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)
