"""Flash attention — a Pallas TPU kernel for the hot op.

The reference has no device kernels of its own (it drives NCCL); on
TPU the framework's hot op is attention, and this module implements it
as a **fused Pallas kernel**: online-softmax over KV blocks so the
(T, T) score matrix never materializes in HBM — scores live in VMEM a
block at a time and the MXU sees two big matmuls per block. Forward
saves the per-row logsumexp; backward recomputes probabilities from it
(the standard memory-for-FLOPs trade) in plain XLA, which fuses well
and keeps the custom_vjp exactly consistent with the kernel's math.

Used via ``TransformerConfig(sp_attention="flash")`` or directly:

    out = flash_attention(q, k, v, causal=True)   # [B, T, H, D] each

On CPU (tests, the virtual mesh) the kernel runs in Pallas interpret
mode automatically.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr, *,
                scale: float, causal: bool, block_q: int, block_k: int,
                seq_len: int):
    """One (batch*head, q-block, kv-block) grid step of the online
    softmax. Scratch (acc, m, l) persists across the kv dimension."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    # Causal block skip: a kv block strictly above the diagonal
    # (every k_pos > every q_pos) contributes nothing — masking it
    # after the matmul would still pay the full MXU cost, which is
    # HALF the causal grid at long sequence (measured ~1.7x forward
    # throughput at seq 8192 on v5e). Skipped steps still issue their
    # K/V block DMAs — clamping the index maps to the last visible
    # block (so Mosaic elides the fetch) measured no faster within
    # run-to-run noise, so the simple monotonic index stays.
    visible = ((qi + 1) * block_q - 1 >= ki * block_k) if causal else True

    @pl.when(visible)
    def _body():
        q = q_ref[0].astype(jnp.float32)            # [bq, d]
        k = k_ref[0].astype(jnp.float32)            # [bk, d]
        v = v_ref[0].astype(jnp.float32)

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale

        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos >= seq_len                     # padded kv rows
        if causal:
            mask = mask | (k_pos > q_pos)
        s = jnp.where(mask, NEG_INF, s)

        m_prev = m_scr[:]                            # [bq, 1]
        l_prev = l_scr[:]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + p.sum(axis=1, keepdims=True)
        acc[:] = acc[:] * alpha + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        l = l_scr[:]
        safe_l = jnp.where(l > 0, l, 1.0)        # fully-masked (pad) rows
        o_ref[0] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[:] + jnp.log(safe_l))[:, 0]


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _default_blocks(t: int):
    """Shape-derived tile sizes. Sequence-spanning blocks win through
    medium sequence — grid overhead dominates small tiles (1024×1024
    at seq 1024 measures 61.6% vs 53.3% MFU for 128×128 on v5e,
    d=2048×8L) — while 512×1024 wins from ~4k up (measured at seq 8192
    for both forward and fwd+bwd). Capped at 1024: ≥2048 blocks exceed
    this environment's compile limits."""
    if t <= 4096:
        b = min(1024, _round_up(t, 128))
        return b, b
    return 512, 1024


def _fwd(q, k, v, *, scale, causal, block_q, block_k, interpret,
         out_dtype=None, q_per_kv: int = 1):
    """q: [BH, T, D]; k/v: [B·Hkv, T, D] with BH = B·Hkv·q_per_kv ->
    (out [BH, T, D], lse [BH, T]).

    GQA runs natively: the K/V BlockSpec index map sends each query
    head's grid step to its kv group's block, so grouped K/V are never
    materialized ``q_per_kv`` times in HBM (the [B,H] flattening is
    batch-major, so ``kv_index = q_index // q_per_kv``)."""
    bh, t, d = q.shape
    out_dtype = q.dtype if out_dtype is None else out_dtype
    bq = min(block_q, _round_up(t, 128))
    bk = min(block_k, _round_up(t, 128))
    tp = _round_up(t, max(bq, bk))
    if tp != t:
        pad = [(0, 0), (0, tp - t), (0, 0)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))

    grid = (bh, tp // bq, tp // bk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_len=t)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d),
                         lambda b, i, j: (b // q_per_kv, j, 0)),
            pl.BlockSpec((1, bk, d),
                         lambda b, i, j: (b // q_per_kv, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            # lse rides a (bh, 1, T) layout so every block's trailing
            # two dims are TPU-tileable (1 == full dim, bq % 128 == 0).
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tp, d), out_dtype),
            jax.ShapeDtypeStruct((bh, 1, tp), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out[:, :t], lse[:, 0, :t]


# Above this query length the backward recompute runs q-chunked: the
# dense form materializes [B·H, Tq, Tk] f32 score/probability tensors
# (O(T²) HBM — ~2 GB per B·H=8 at T=8192, OOM well before 32k); the
# chunked form caps live intermediates at [B·H, chunk, Tk].
_BWD_CHUNK_T = 4096
_BWD_CHUNK = 1024


def _bwd(scale, causal, residuals, g, g_lse=None, q_per_kv: int = 1):
    """Recompute-based backward from the saved logsumexp: exact same
    probabilities the kernel computed, expressed as XLA matmul chains
    (fused by the compiler). ``g_lse`` carries the logsumexp cotangent
    when the caller consumed it (ring-attention block merging);
    d lse/d q = (p @ k)·scale and d lse/d k_j = p_j · q · scale.

    GQA (``q_per_kv > 1``): q-side tensors reshape to a [B·Hkv, rep]
    grouping (consecutive query heads share a kv head under the
    batch-major flattening) and dk/dv sum over the group.

    Long sequences dispatch to the q-chunked form (same math, bounded
    memory)."""
    if residuals[0].shape[1] > _BWD_CHUNK_T:
        return _bwd_chunked(scale, causal, residuals, g, g_lse, q_per_kv)
    q, k, v, out, lse = residuals
    rep = q_per_kv
    bkv = k.shape[0]
    t = q.shape[1]
    d = q.shape[2]
    as_grp = lambda x: x.astype(jnp.float32).reshape(bkv, rep, t, d)  # noqa: E731
    gl = (None if g_lse is None
          else g_lse.astype(jnp.float32).reshape(bkv, rep, t))
    dq, dk, dv = _bwd_rows(
        as_grp(q), as_grp(g), as_grp(out), lse.reshape(bkv, rep, t), gl,
        k.astype(jnp.float32), v.astype(jnp.float32), 0, scale, causal)
    return (dq.reshape(q.shape).astype(q.dtype), dk.astype(k.dtype),
            dv.astype(v.dtype))


def _bwd_rows(qc, doc, outc, lsec, glc, kf, vf, q_pos0, scale, causal):
    """Gradient contributions of one block of query rows (f32 in/out):
    the shared body of the dense and chunked backwards. ``q_pos0`` is
    the block's global query offset for the causal mask."""
    tk = kf.shape[1]
    s = jnp.einsum("brqd,bkd->brqk", qc, kf) * scale
    if causal:
        q_pos = q_pos0 + jnp.arange(qc.shape[2])[:, None]
        k_pos = jnp.arange(tk)[None, :]
        s = jnp.where(k_pos > q_pos, NEG_INF, s)
    p = jnp.exp(s - lsec[..., None])             # [bkv, rep, rows, tk]

    dv = jnp.einsum("brqk,brqd->bkd", p, doc)
    dp = jnp.einsum("brqd,bkd->brqk", doc, vf)
    delta = jnp.sum(doc * outc, axis=-1, keepdims=True)
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("brqk,bkd->brqd", ds, kf)
    dk = jnp.einsum("brqk,brqd->bkd", ds, qc)
    if glc is not None:
        dq = dq + glc[..., None] * jnp.einsum("brqk,bkd->brqd", p, kf) * scale
        dk = dk + jnp.einsum("brq,brqk,brqd->bkd", glc, p, qc) * scale
    return dq, dk, dv


def _bwd_chunked(scale, causal, residuals, g, g_lse, q_per_kv):
    """The backward above with the query axis processed in
    ``_BWD_CHUNK``-row slices under ``lax.scan``: per-step tensors are
    [bkv, rep, chunk, tk] instead of [bkv, rep, tq, tk], so HBM stays
    bounded for long sequences. Padding rows (q/do/out zeros, lse 0)
    contribute exactly zero to every accumulated gradient."""
    q, k, v, out, lse = residuals
    rep = q_per_kv
    bkv = k.shape[0]
    t, d = q.shape[1], q.shape[2]
    chunk = _BWD_CHUNK
    pad = (-t) % chunk

    def prep(x):  # [bkv*rep, t, d] -> padded [bkv, rep, T, d], own dtype
        x = x.reshape(bkv, rep, t, d)
        return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))

    # Padded in the INPUT dtype: the f32 cast happens per chunk inside
    # step(), keeping the f32 working set at O(chunk), not O(T).
    qf, do, outf = prep(q), prep(g), prep(out)
    lseg = jnp.pad(lse.reshape(bkv, rep, t), ((0, 0), (0, 0), (0, pad)))
    gl = (None if g_lse is None else
          jnp.pad(g_lse.astype(jnp.float32).reshape(bkv, rep, t),
                  ((0, 0), (0, 0), (0, pad))))
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    n = (t + pad) // chunk

    def step(carry, i):
        dk_acc, dv_acc = carry
        sl = functools.partial(jax.lax.dynamic_slice_in_dim,
                               start_index=i * chunk, slice_size=chunk,
                               axis=2)
        f32 = lambda x: sl(x).astype(jnp.float32)  # noqa: E731
        dq_c, dk_c, dv_c = _bwd_rows(
            f32(qf), f32(do), f32(outf), sl(lseg),
            None if gl is None else sl(gl), kf, vf, i * chunk, scale,
            causal)
        return (dk_acc + dk_c, dv_acc + dv_c), dq_c.astype(q.dtype)

    (dk, dv), dq_chunks = jax.lax.scan(
        step, (jnp.zeros_like(kf), jnp.zeros_like(vf)), jnp.arange(n))
    # [n, bkv, rep, chunk, d] -> [bkv, rep, t, d] (pad rows dropped)
    dq = jnp.moveaxis(dq_chunks, 0, 2).reshape(
        bkv, rep, n * chunk, d)[:, :, :t, :]
    return (dq.reshape(q.shape), dk.astype(k.dtype), dv.astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret, q_per_kv):
    out, _ = _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, interpret=interpret, q_per_kv=q_per_kv)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
               q_per_kv):
    out, lse = _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                    block_k=block_k, interpret=interpret,
                    q_per_kv=q_per_kv)
    return out, (q, k, v, out, lse)


def _flash_bwd(scale, causal, block_q, block_k, interpret, q_per_kv,
               residuals, g):
    return _bwd(scale, causal, residuals, g, q_per_kv=q_per_kv)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash_lse(q, k, v, scale, causal, block_q, block_k, interpret,
               out_dtype, q_per_kv):
    return _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                block_k=block_k, interpret=interpret, out_dtype=out_dtype,
                q_per_kv=q_per_kv)


def _flash_lse_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                   out_dtype, q_per_kv):
    out, lse = _fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                    block_k=block_k, interpret=interpret,
                    out_dtype=out_dtype, q_per_kv=q_per_kv)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(scale, causal, block_q, block_k, interpret, out_dtype,
                   q_per_kv, residuals, g):
    g_out, g_lse = g
    return _bwd(scale, causal, residuals, g_out, g_lse, q_per_kv=q_per_kv)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_with_lse(q, k, v, *, causal: bool = False,
                             scale: Optional[float] = None,
                             block_q: Optional[int] = None,
                             block_k: Optional[int] = None,
                             interpret: Optional[bool] = None,
                             out_dtype=None):
    """``[BH, T, D]``-layout flash attention returning ``(out, lse)``
    — the building block for blockwise composition (ring attention
    merges per-chunk results by logsumexp weighting). Differentiable
    in both outputs. ``out_dtype=jnp.float32`` keeps chunk outputs at
    merge precision (callers that round once at the end)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    dq, dk = _default_blocks(q.shape[1])
    return _flash_lse(q, k, v, float(scale), causal,
                      dq if block_q is None else block_q,
                      dk if block_k is None else block_k, interpret,
                      jnp.dtype(out_dtype) if out_dtype else None, 1)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """Fused attention over ``[B, T, H, D]`` q with ``[B, T, Hkv, D]``
    k/v, ``H % Hkv == 0`` — **GQA runs natively**: grouped K/V are read
    by index-map inside the kernel, never materialized per query head
    (an Hkv=H/4 model moves 4× less K/V through HBM than pre-tiling).
    Differentiable via custom VJP.

    Block sizes default by SHAPE (``_default_blocks``): sequence-
    spanning tiles through seq 4096, 512×1024 beyond (measured on v5e
    at seq 8192, with the causal block skip, 512×1024 is fastest for
    BOTH forward and fwd+bwd — 1.6× the old 128×128 tiles, whose grid
    overhead dwarfs their cache friendliness). Pass explicit values to
    override."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if interpret is None:
        interpret = jax.default_backend() == "cpu"
    b, t, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv or v.shape[2] != hkv:
        raise ValueError(
            f"q heads ({h}) must be a multiple of kv heads ({hkv}); "
            f"v has {v.shape[2]}")

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * x.shape[2], t, d)

    dq, dk = _default_blocks(t)
    out = _flash(to_bh(q), to_bh(k), to_bh(v), float(scale), causal,
                 dq if block_q is None else block_q,
                 dk if block_k is None else block_k, interpret, h // hkv)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
