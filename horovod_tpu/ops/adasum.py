"""Adasum — scaling-insensitive gradient reduction, TPU-native.

Rebuild of the reference's Adasum operator family
(``horovod/common/ops/adasum/adasum.h:166-330``): instead of averaging,
two gradients ``a``, ``b`` are combined with the projection rule

    adasum(a, b) = (1 - a·b / (2|a|²)) · a  +  (1 - a·b / (2|b|²)) · b

which keeps the update magnitude stable as the number of workers grows
(orthogonal gradients add; identical gradients average). A world-sized
reduction applies the rule over a binary tree of pairings — the
reference's vector-halving distance-doubling (VHDD) is a
bandwidth-optimal schedule of exactly that tree.

Two tiers here, matching the rest of :mod:`horovod_tpu.ops`:

* :func:`adasum_allreduce` — in-``jit`` SPMD under ``shard_map``: XOR
  distance-doubling with ``lax.ppermute`` full-vector exchanges. Each
  of the log2(P) rounds both partners compute the identical symmetric
  combine, so no broadcast leg is needed. Dot products and norms are
  accumulated per tensor (per pytree leaf) in f32 — the per-tensor
  weighting of the reference (``adasum.h:101-122``), with XLA fusing
  the elementwise work into the exchange.
* The eager named-tensor path executes Adasum in the native core
  (``native/src/ops.cc AdasumAllreduce``) with f64 host accumulation;
  ``hvd.allreduce(t, op=hvd.Adasum)`` routes there.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.ops.collectives import AxisName, axis_size


def adasum_combine(a, b):
    """Combine two same-shaped tensors with the Adasum projection rule.

    Zero-norm operands pass the other side through unchanged (the
    reference guards the same division, ``adasum.h:258-266``). Math runs
    in f32 (f64 when jax x64 is enabled and inputs are f64); the result
    is cast back to the input dtype.
    """
    if not (jnp.issubdtype(a.dtype, jnp.inexact) and
            jnp.issubdtype(b.dtype, jnp.inexact)):
        raise TypeError(f"adasum is defined for float dtypes, got {a.dtype}")
    acc = jnp.promote_types(a.dtype, jnp.float32)
    af, bf = a.astype(acc), b.astype(acc)
    dot = jnp.vdot(af, bf)
    na2 = jnp.vdot(af, af)
    nb2 = jnp.vdot(bf, bf)
    ac = jnp.where(na2 > 0, 1.0 - dot / (2.0 * jnp.where(na2 > 0, na2, 1.0)),
                   1.0)
    bc = jnp.where(nb2 > 0, 1.0 - dot / (2.0 * jnp.where(nb2 > 0, nb2, 1.0)),
                   1.0)
    return (ac * af + bc * bf).astype(a.dtype)


def adasum_allreduce(tree: Any, axis_name: AxisName = "dp"):
    """Adasum-allreduce a pytree across ``axis_name`` inside
    ``shard_map``/``pjit``.

    The axis size must be a power of two (the natural shape of the
    distance-doubling tree; the eager tier handles ragged world sizes
    with a fold step). Per-tensor weighting: each leaf gets its own
    dot/norm coefficients per round, exactly like the reference's
    per-layer Adasum.
    """
    n = axis_size(axis_name)
    if n & (n - 1):
        raise ValueError(
            f"adasum_allreduce needs a power-of-two axis size, got {n} "
            f"(use the eager hvd.allreduce(op=Adasum) path for ragged "
            f"world sizes)")
    d = 1
    while d < n:
        perm = [(i, i ^ d) for i in range(n)]
        theirs = jax.tree.map(
            lambda x: lax.ppermute(x, axis_name, perm=perm), tree)
        tree = jax.tree.map(adasum_combine, tree, theirs)
        d *= 2
    # Every shard now holds the identical result, but ppermute outputs
    # are device-varying to the type system; a pmax over equal values
    # re-establishes the replicated type (same trick as PRODUCT in
    # collectives.py) so callers can use out_specs=P().
    return jax.tree.map(lambda x: lax.pmax(x, axis_name), tree)
