"""Framework-neutral bootstrap/checkpoint helpers.

Rebuild of ``horovod/torch/functions.py:190,233`` (``broadcast_object``
/ ``allgather_object``: pickle over byte tensors) with numpy as the
wire format; the torch and jax bindings re-export these and add
framework-specific parameter sync.
"""

from __future__ import annotations

import io
from typing import Any, List, Optional

import cloudpickle
import numpy as np

import horovod_tpu.api as api


def broadcast_object(obj: Any, root_rank: int = 0,
                     name: Optional[str] = None) -> Any:
    """Broadcast an arbitrary picklable object from ``root_rank``.

    Two collectives, as in the reference: the byte length first (shapes
    must agree on every rank before the payload broadcast can be
    validated), then the payload itself.
    """
    name = name or "broadcast_object"
    if api.rank() == root_rank:
        payload = np.frombuffer(cloudpickle.dumps(obj), dtype=np.uint8)
        length = np.asarray([payload.size], dtype=np.int64)
    else:
        payload = None
        length = np.zeros(1, dtype=np.int64)
    length = api.broadcast(length, root_rank=root_rank, name=f"{name}.len")
    if payload is None:
        payload = np.zeros(int(length[0]), dtype=np.uint8)
    payload = api.broadcast(payload, root_rank=root_rank,
                            name=f"{name}.data")
    return cloudpickle.loads(payload.tobytes())


def allgather_object(obj: Any, name: Optional[str] = None) -> List[Any]:
    """Gather one picklable object per rank; returns them ordered by
    rank (reference ``allgather_object``, ``torch/functions.py:233``).

    Relies on allgather's variable first-dimension support — payload
    sizes may differ per rank — with a size allgather first so the
    concatenated buffer can be split back.
    """
    name = name or "allgather_object"
    payload = np.frombuffer(cloudpickle.dumps(obj), dtype=np.uint8)
    sizes = api.allgather(np.asarray([payload.size], dtype=np.int64),
                          name=f"{name}.len")
    gathered = api.allgather(payload, name=f"{name}.data")
    out: List[Any] = []
    offset = 0
    for sz in sizes:
        sz = int(sz)
        out.append(cloudpickle.loads(gathered[offset:offset + sz].tobytes()))
        offset += sz
    return out
