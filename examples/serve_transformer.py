"""Continuous-batching inference serving on the sharded transformer.

Shows the serve tier end to end: build (or shard) a decoder LM,
stand up a :class:`horovod_tpu.serve.ServeEngine`, submit a burst of
mixed-length requests with per-request deadlines, drive the scheduler,
and read back tokens + the throughput/latency metrics surface.

CPU smoke (no accelerator needed):
  JAX_PLATFORMS=cpu python examples/serve_transformer.py --tiny

Tensor-parallel over 8 virtual devices:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
      python examples/serve_transformer.py --tiny --tp 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--max-new", type=int, default=16)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel mesh axis for serving")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable content-addressed KV block reuse")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="max prefill tokens per engine step (long "
                         "prompts stream in chunks between decode "
                         "iterations)")
    ap.add_argument("--system-prompt", type=int, default=0,
                    help="prepend this many shared tokens to every "
                         "request (shows the prefix cache working)")
    ap.add_argument("--tiny", action="store_true",
                    help="2-layer d=64 model (CPU smoke)")
    ap.add_argument("--platform", default=None, choices=[None, "cpu", "tpu"])
    ap.add_argument("--trace-out", default=None,
                    help="write a chrome-tracing timeline of the "
                         "scheduler steps")
    args = ap.parse_args()

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu.models import TransformerConfig, init_transformer
    from horovod_tpu.serve import ServeConfig, ServeEngine, make_trace

    cfg = (TransformerConfig.tiny(dtype=jnp.float32, remat=False)
           if args.tiny else
           TransformerConfig(vocab_size=8192, d_model=512, n_layers=4,
                             n_heads=8, n_kv_heads=4, d_ff=1376,
                             max_seq=1024, dtype=jnp.bfloat16,
                             remat=False))
    mesh = None
    if args.tp > 1:
        from horovod_tpu.parallel import build_mesh
        mesh = build_mesh(dp=-1, tp=args.tp)
    params = init_transformer(cfg, jax.random.PRNGKey(0), mesh)

    max_prompt = min(32 + args.system_prompt,
                     cfg.max_seq - args.max_new - 1)
    if args.system_prompt >= max_prompt:
        ap.error(f"--system-prompt {args.system_prompt} leaves no room "
                 f"for a request within this model's budget "
                 f"(max prompt {max_prompt} at --max-new {args.max_new})")
    engine = ServeEngine(
        cfg, params,
        ServeConfig(max_batch=args.max_batch, block_size=args.block_size,
                    max_prompt=max_prompt, max_new_tokens=args.max_new,
                    max_queue=max(args.requests, 8),
                    prefix_caching=not args.no_prefix_cache,
                    prefill_chunk=args.prefill_chunk),
        mesh=mesh)

    trace = make_trace(args.requests, seed=0,
                       max_prompt=max_prompt - args.system_prompt,
                       max_new=args.max_new, vocab=cfg.vocab_size)
    if args.system_prompt:
        sys_tokens = np.random.RandomState(7).randint(
            1, cfg.vocab_size, size=args.system_prompt).tolist()
        trace = [(sys_tokens + p, n) for p, n in trace]
    import time
    rids = []
    for prompt, max_new in trace:
        # A deadline 30s out: comfortably met here, but shows the knob
        # (stale requests get a 503-style "expired" result instead of
        # burning prefill FLOPs).
        rids.append(engine.submit(prompt, max_new,
                                  deadline=time.perf_counter() + 30.0))

    while engine.pending:
        engine.step()

    for rid in rids[:4]:
        res = engine.result(rid)
        lat = res.first_token_latency_s
        lat = "n/a" if lat is None else f"{lat * 1e3:.1f}ms"
        print(f"request {rid}: {res.status} "
              f"prompt_len={res.n_prompt} -> {len(res.tokens)} tokens "
              f"first_token={lat} "
              f"tokens={res.tokens[:8]}{'...' if len(res.tokens) > 8 else ''}")
    print(f"... and {len(rids) - 4} more")

    snap = engine.metrics.snapshot()
    print("serve metrics:",
          {k: snap[k] for k in ("tokens_per_sec", "batch_occupancy",
                                "p50_first_token_ms", "p99_first_token_ms",
                                "p50_per_token_ms", "p99_per_token_ms",
                                "requests_finished")})
    print("kv pool:",
          {k: snap[k] for k in ("kv_blocks_in_use", "kv_blocks_cached",
                                "kv_blocks_high_water",
                                "prefix_cache_hit_rate",
                                "prefix_block_hits",
                                "prefix_block_evictions")})
    if args.trace_out:
        engine.metrics.export_chrome_trace(args.trace_out)
        print(f"chrome trace written to {args.trace_out}")


if __name__ == "__main__":
    main()
