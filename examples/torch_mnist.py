"""Distributed PyTorch training example — MNIST semantics.

The shape of the reference's ``examples/pytorch/pytorch_mnist.py``:
``hvd.init``, shard the dataset by rank, wrap the optimizer in
``DistributedOptimizer`` with named parameters, broadcast initial
parameters and optimizer state from rank 0, train, and average the
validation metric across ranks at the end.

Data is synthetic (label = a linear+nonlinear function of the image) so
the example runs hermetically — no dataset download — while the loss
still demonstrably falls.

Run:  horovodrun -np 4 python examples/torch_mnist.py --epochs 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import torch  # noqa: E402
import torch.nn as nn  # noqa: E402
import torch.nn.functional as F  # noqa: E402
import torch.utils.data  # noqa: E402

import horovod_tpu.torch as hvd  # noqa: E402


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 8, kernel_size=5)
        self.conv2 = nn.Conv2d(8, 16, kernel_size=5)
        self.fc1 = nn.Linear(256, 64)
        self.fc2 = nn.Linear(64, 10)

    def forward(self, x):
        x = F.relu(F.max_pool2d(self.conv1(x), 2))
        x = F.relu(F.max_pool2d(self.conv2(x), 2))
        x = x.flatten(1)
        x = F.relu(self.fc1(x))
        return F.log_softmax(self.fc2(x), dim=1)


def synthetic_mnist(n: int, seed: int):
    g = torch.Generator().manual_seed(seed)
    x = torch.randn(n, 1, 28, 28, generator=g)
    # Deterministic learnable labels: sign pattern of pixel-block sums.
    blocks = x.reshape(n, 1, 4, 7, 4, 7).mean(dim=(3, 5)).reshape(n, 16)
    y = (blocks[:, :10].argmax(dim=1))
    return torch.utils.data.TensorDataset(x, y)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--train-size", type=int, default=2048)
    parser.add_argument("--seed", type=int, default=42)
    args = parser.parse_args()

    hvd.init()
    torch.manual_seed(args.seed)

    # Shard the dataset: each rank sees a distinct contiguous slice
    # (the DistributedSampler role).
    full = synthetic_mnist(args.train_size, args.seed)
    shard = args.train_size // hvd.size()
    lo = hvd.rank() * shard
    train = torch.utils.data.Subset(full, range(lo, lo + shard))
    loader = torch.utils.data.DataLoader(
        train, batch_size=args.batch_size, shuffle=True,
        generator=torch.Generator().manual_seed(args.seed + hvd.rank()))

    model = Net()
    optimizer = torch.optim.SGD(model.parameters(), lr=args.lr,
                                momentum=0.9)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters())

    # Everyone starts from rank 0's weights and optimizer state.
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    first_loss = last_loss = None
    for epoch in range(args.epochs):
        model.train()
        for batch_idx, (data, target) in enumerate(loader):
            optimizer.zero_grad()
            loss = F.nll_loss(model(data), target)
            loss.backward()
            optimizer.step()
            if first_loss is None:
                first_loss = loss.item()
            last_loss = loss.item()
        # Epoch metric, averaged across ranks (MetricAverageCallback
        # semantics).
        avg = hvd.allreduce(torch.tensor([last_loss]), op=hvd.Average,
                            name=f"epoch_loss.{epoch}")
        if hvd.rank() == 0:
            print(f"epoch {epoch}: mean rank loss {float(avg[0]):.4f}")

    improved = first_loss is None or last_loss < first_loss
    print(f"rank {hvd.rank()}: first_loss={first_loss:.4f} "
          f"last_loss={last_loss:.4f} improved={improved}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
