"""Serving-fleet demo: a multi-replica router over in-process engines.

Shows the fleet tier end to end: N engine replicas behind
:class:`horovod_tpu.serve.ServeRouter` — cache-affinity placement of
multi-tenant traffic (each tenant shares a system prompt), optional
prefill/decode pool split with KV handoff, deadline-class load
shedding under a deliberately tiny router queue, and the one-scrape
fleet telemetry (per-replica ``serve_*{instance=...}`` series plus
the ``serve_fleet_*`` rollup).

CPU smoke (no accelerator needed):
  JAX_PLATFORMS=cpu python examples/serve_fleet.py --tiny

Split prefill/decode pools:
  JAX_PLATFORMS=cpu python examples/serve_fleet.py --tiny --prefill 1

Cross-process fleet (each replica a spawned ``bin/hvd-serve-worker``
process behind the RPC seam; add --kv-compression bf16 to halve
KV-handoff bytes on a split fleet):
  JAX_PLATFORMS=cpu python examples/serve_fleet.py --tiny --cross-process

Speculative draft/target pair (the target replicas decode with a
1-layer draft proposing k tokens per step — greedy streams stay
bitwise plain decode's — AND the draft registers as its own model
group, served directly via ``model="draft"``):
  JAX_PLATFORMS=cpu python examples/serve_fleet.py --tiny --draft
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=4)
    ap.add_argument("--prefill", type=int, default=0,
                    help="replicas in the prefill pool (0 = unified; "
                         "the rest decode and receive KV handoffs)")
    ap.add_argument("--tenants", type=int, default=4,
                    help="distinct shared system prompts in the trace")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--placement", default="affinity",
                    choices=["affinity", "least", "random", "round_robin"])
    ap.add_argument("--shed-demo", action="store_true",
                    help="also demo deadline-class shedding through a "
                         "deliberately tiny router queue")
    ap.add_argument("--cross-process", action="store_true",
                    help="spawn each replica as a bin/hvd-serve-worker "
                         "process and route to it over the RPC seam "
                         "(docs/serving.md 'Cross-process fleet')")
    ap.add_argument("--kv-compression", default=None,
                    choices=[None, "bf16", "fp16"],
                    help="wire codec for KV pages on cross-process "
                         "handoffs (bf16 halves migration bytes)")
    ap.add_argument("--draft", action="store_true",
                    help="speculative draft/target demo: serve the "
                         "target fleet with a 1-layer draft proposing "
                         "--spec-k tokens per step, and register the "
                         "draft as its own model group (multi-model "
                         "routing) served via model='draft'")
    ap.add_argument("--spec-k", type=int, default=3)
    ap.add_argument("--tiny", action="store_true",
                    help="2-layer d=64 model (CPU smoke)")
    ap.add_argument("--platform", default=None, choices=[None, "cpu", "tpu"])
    args = ap.parse_args()

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from horovod_tpu.models import TransformerConfig, init_transformer
    from horovod_tpu.serve import (
        DraftConfig, FleetSaturated, RouterConfig, ServeConfig,
        ServeRouter, make_multi_tenant_trace,
    )

    cfg = (TransformerConfig.tiny(dtype=jnp.float32, remat=False)
           if args.tiny else
           TransformerConfig(vocab_size=8192, d_model=512, n_layers=4,
                             n_heads=8, n_kv_heads=4, d_ff=1376,
                             max_seq=1024, dtype=jnp.bfloat16,
                             remat=False))
    # Workers rebuild the same params from (config, seed 0); the
    # router only needs them materialized for in-process replicas.
    params = (None if args.cross_process
              else init_transformer(cfg, jax.random.PRNGKey(0)))

    draft_cfg = None
    spec_kw = {}
    if args.draft:
        # 1-layer draft of the target's width; the engine rebuilds its
        # params from (config, seed) — the cross-process contract too.
        import dataclasses as _dc
        draft_cfg = _dc.replace(cfg, n_layers=1)
        spec_kw = dict(draft=DraftConfig(draft_cfg, seed=0),
                       spec_k=args.spec_k)
        if not args.cross_process:
            # In-process: use the idealized pair (the target's extra
            # layers contribute zero to the residual stream, so it
            # computes the draft's exact logits) — accept rate 1.0
            # shows the mechanism paying. Random-weight pairs (the
            # cross-process path, where workers rebuild params from
            # the seed) honestly show accept ~0: a real deployment
            # needs a draft trained to agree with its target.
            from horovod_tpu.serve import make_draft_target_params
            cfg, params = make_draft_target_params(
                draft_cfg, n_layers=cfg.n_layers, seed=0)

    trace = make_multi_tenant_trace(
        args.requests, seed=0, n_tenants=args.tenants, prefix_len=16,
        min_new=2, max_new=args.max_new, vocab=cfg.vocab_size)
    max_prompt = max(len(p) for p, _ in trace)
    serve_cfg = ServeConfig(
        max_batch=4, max_queue=max(args.requests, 8), block_size=8,
        max_prompt=max_prompt, max_new_tokens=args.max_new, **spec_kw)
    workers = []
    if args.cross_process:
        from horovod_tpu.serve import spawn_worker
        print(f"spawning {args.replicas} hvd-serve-worker processes...")
        workers = [spawn_worker(via_bin=True)
                   for _ in range(args.replicas)]
        for w in workers:
            print(f"  worker pid={w.proc.pid} port={w.port}")
    router = ServeRouter(
        cfg, params,
        RouterConfig(n_replicas=args.replicas, n_prefill=args.prefill,
                     # +4: the --draft demo queues a few draft-model
                     # requests alongside the full target trace.
                     max_queue=max(args.requests, 8) + 4,
                     placement=args.placement,
                     handoff_compression=args.kv_compression),
        serve_cfg, workers=workers or None, worker_seed=0)

    draft_rids = []
    if args.draft and args.cross_process:
        print("note: --draft with --cross-process serves speculatively "
              "(workers rebuild the draft from the seed; random pairs "
              "accept ~0) but skips the in-process draft model group")
    if args.draft and not args.cross_process:
        # Multi-model: the draft is also an ordinary fleet member —
        # its own model group, routed by model=, never mixed with the
        # target's replicas.
        draft_params = init_transformer(draft_cfg, jax.random.PRNGKey(0))
        router.add_model(
            "draft", draft_cfg, draft_params, n_replicas=1,
            serve_cfg=ServeConfig(
                max_batch=4, max_queue=max(args.requests, 8),
                block_size=8, max_prompt=max_prompt,
                max_new_tokens=args.max_new))
        draft_rids = [router.submit(p, n, model="draft")
                      for p, n in trace[:4]]

    rids = [router.submit(p, n) for p, n in trace]
    router.run_until_idle()

    by_replica = {}
    for rid, inst, match, _cost in router.placement_log:
        by_replica.setdefault(inst, []).append((rid, match))
    print(f"fleet: {args.replicas} replicas "
          f"({args.prefill} prefill / "
          f"{args.replicas - args.prefill if args.prefill else 0} decode)"
          if args.prefill else
          f"fleet: {args.replicas} unified replicas")
    for inst in sorted(by_replica):
        placed = by_replica[inst]
        hits = sum(1 for _, m in placed if m > 0)
        print(f"  replica {inst}: {len(placed)} requests placed, "
              f"{hits} with a warm chain prefix")
    ok = sum(1 for r in rids if router.result(r).status == "ok")
    print(f"served {ok}/{len(rids)} ok")
    if args.draft:
        snap = router.metrics.snapshot()
        print(f"speculative: accept_rate={snap['spec_accept_rate']} "
              f"({int(snap['spec_accepted_total'])}/"
              f"{int(snap['spec_proposed_total'])} draft tokens "
              f"accepted at k={args.spec_k})")
        if draft_rids:
            d_ok = sum(1 for r in draft_rids
                       if router.result(r).status == "ok")
            by_model = router.metrics.snapshot_by_model()
            print(f"draft model group: {d_ok}/{len(draft_rids)} ok, "
                  f"replicas={int(by_model['draft']['replicas'])}, "
                  f"finished="
                  f"{int(by_model['draft']['requests_finished'])}")

    snap = router.metrics.snapshot()
    print("fleet metrics:",
          {k: snap[k] for k in ("tokens_per_sec", "batch_occupancy",
                                "prefix_cache_hit_rate",
                                "p99_first_token_ms", "placed_affinity",
                                "placed_fallback", "handoffs",
                                "requests_finished")})

    if args.cross_process:
        wire = sum(w.conn.span_wire_bytes for w in workers)
        raw = sum(w.conn.span_raw_bytes for w in workers)
        rpcs = sum(w.conn.msgs_sent for w in workers)
        print(f"rpc plane: {rpcs} calls, heartbeats="
              f"{snap['heartbeats']}, kv bytes {wire}/{raw} wire/raw"
              + (f" ({100 * (raw - wire) / raw:.0f}% saved)"
                 if raw > wire else ""))
        router.close()

    if args.shed_demo:
        print("\n-- shedding demo (router queue cap 2) --")
        if params is None:
            params = init_transformer(cfg, jax.random.PRNGKey(0))
        shed_router = ServeRouter(
            cfg, params,
            RouterConfig(n_replicas=1, max_queue=2), serve_cfg)
        a = shed_router.submit(trace[0][0], 2, deadline_class=2)
        shed_router.submit(trace[1][0], 2, deadline_class=1)
        shed_router.submit(trace[2][0], 2, deadline_class=0)
        res = shed_router.result(a)
        print(f"victim: status={res.status} reason={res.reason} "
              f"class={res.deadline_class} "
              f"retry_after={res.retry_after_s}s")
        try:
            shed_router.submit(trace[3][0], 2, deadline_class=2)
        except FleetSaturated as e:
            print(f"arrival rejected: reason={e.reason} "
                  f"class={e.deadline_class} retry_after={e.retry_after_s}s")
        shed_router.run_until_idle()

    # One scrape covers every replica + the rollup.
    from horovod_tpu.metrics import metrics_prometheus
    frag = [ln for ln in metrics_prometheus().splitlines()
            if ln.startswith(("serve_fleet_replicas",
                              "serve_fleet_tokens_per_sec",
                              "serve_fleet_shed_total"))]
    print("\nfleet exposition fragment:")
    for ln in frag:
        print(" ", ln)


if __name__ == "__main__":
    main()
