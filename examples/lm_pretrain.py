"""Decoder-LM pretraining on the in-jit SPMD tier — the idiomatic
TPU path: ONE process drives the whole device mesh, parallelism is
declared as mesh axes, and XLA inserts every collective.

This is the tier the eager examples point at for performance; it has
no reference analog (the reference is process-per-rank only, this is
the TPU-first redesign). Shows: mesh construction (dp/fsdp/tp/sp/pp),
``make_train_step`` (scan-over-layers Llama-family model, remat,
sharded optimizer state) or the pipelined factories
(``--pp N --pp-schedule gpipe|1f1b``), synthetic token stream, loss
logging, and a final-checkpoint save via ``orbax`` when available.

Run (any device count; axes auto-fold to what exists):
  python examples/lm_pretrain.py --steps 20 --dp 2 --tp 2
CPU smoke (8 virtual devices):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/lm_pretrain.py --platform cpu --steps 2 --tiny
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8, help="global batch")
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--dp", type=int, default=-1)
    ap.add_argument("--fsdp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1,
                    help="pipeline stages (composes with dp/fsdp/tp)")
    ap.add_argument("--pp-schedule", default="gpipe",
                    choices=["gpipe", "1f1b"],
                    help="gpipe: AD-replayed; 1f1b: interleaved "
                         "backward, O(pp) activation residency")
    ap.add_argument("--n-micro", type=int, default=2,
                    help="microbatches per step when --pp > 1")
    ap.add_argument("--moe", action="store_true",
                    help="mixture-of-experts FFN (8 experts, top-2, "
                         "GShard capacity routing); with --ep > 1 the "
                         "dispatch runs as the quantized-alltoall "
                         "shard_map island (docs/perf_tuning.md)")
    ap.add_argument("--ep", type=int, default=1,
                    help="expert-parallel axis size (-1 = all remaining "
                         "devices); requires --moe")
    ap.add_argument("--moe-compression", default="int8",
                    choices=["none", "bf16", "int8"],
                    help="island dispatch codec (none = bitwise the "
                         "GSPMD einsum path)")
    ap.add_argument("--tiny", action="store_true",
                    help="2-layer d=64 model (CI smoke)")
    ap.add_argument("--platform", default=None, choices=[None, "cpu", "tpu"])
    ap.add_argument("--out", default=None,
                    help="orbax checkpoint dir (optional)")
    args = ap.parse_args()

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.models import TransformerConfig, make_train_step
    from horovod_tpu.parallel import (build_mesh, make_pp_train_step,
                                      make_pp_train_step_1f1b)

    if args.ep != 1 and not args.moe:
        ap.error("--ep needs --moe (the axis only shards experts)")
    mesh = build_mesh(dp=args.dp, fsdp=args.fsdp, tp=args.tp, sp=args.sp,
                      pp=args.pp, ep=args.ep)
    # MoE: 8 experts, top-2; with ep > 1 the dispatch/combine hops run
    # as the quantized-alltoall island (make_train_step builds it from
    # these cfg fields — codec "none" routes back to the exact GSPMD
    # einsum path by construction).
    ep_size = mesh.shape.get("ep", 1)
    moe_kw = dict(n_experts=8, moe_top_k=2,
                  moe_dispatch="island" if ep_size > 1 else None,
                  moe_compression=args.moe_compression
                  if ep_size > 1 else None) if args.moe else {}
    if args.tiny:
        cfg = TransformerConfig.tiny(max_seq=args.seq, **moe_kw)
    else:
        cfg = TransformerConfig(
            vocab_size=8192, d_model=512, n_layers=4, n_heads=8,
            n_kv_heads=8, d_ff=1376, max_seq=args.seq,
            dtype=jnp.bfloat16,
            sp_attention="ring" if args.sp > 1 else "local", **moe_kw)

    if args.pp > 1:
        factory = (make_pp_train_step_1f1b
                   if args.pp_schedule == "1f1b" else make_pp_train_step)
        init_state, step, _ = factory(cfg, mesh, n_micro=args.n_micro)
    else:
        init_state, step, _ = make_train_step(cfg, mesh)
    state = jax.jit(init_state)(jax.random.PRNGKey(0))
    n_params = sum(int(x.size) for x in jax.tree.leaves(state["params"]))
    print(f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))} "
          f"params={n_params:,}")

    # Synthetic token stream: a fixed random corpus sampled per step
    # (hermetic; swap in a real tokenized dataset loader here).
    data_sharding = NamedSharding(mesh, P(("dp", "fsdp"), None))
    corpus = jax.random.randint(jax.random.PRNGKey(1),
                                (64, args.seq + 1), 0, cfg.vocab_size)

    loss = float("nan")  # --steps 0 still reaches the DONE line
    for i in range(args.steps):
        idx = jax.random.randint(jax.random.PRNGKey(100 + i),
                                 (args.batch,), 0, corpus.shape[0])
        batch = {"tokens": jax.device_put(corpus[idx], data_sharding)}
        state, loss = step(state, batch)
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")

    if args.out:
        try:
            import orbax.checkpoint as ocp
            ckptr = ocp.StandardCheckpointer()
            ckptr.save(os.path.abspath(args.out),
                       jax.device_get(state["params"]), force=True)
            ckptr.wait_until_finished()
            print(f"saved params to {args.out}")
        except ImportError:
            print("orbax not installed; skipping checkpoint", file=sys.stderr)

    print(f"DONE loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
