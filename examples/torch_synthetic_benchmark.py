"""Synthetic training benchmark — the torch eager tier.

Mirrors the reference's ``examples/pytorch/pytorch_synthetic_benchmark
.py:19-118`` protocol: synthetic ImageNet-shaped data, ``--batch-size``
per process, ``--num-warmup-batches`` then ``--num-iters`` timed rounds
of ``--num-batches-per-iter`` batches; reports img/sec per process
(mean ± 1.96σ) and the allreduced total. ``--fp16-allreduce`` compresses
gradients on the wire; gradient reduction rides the hook-based
``DistributedOptimizer`` (tensor fusion + response cache underneath).

torchvision models are used when installed (``--model resnet50``); the
built-in ``tiny`` CNN keeps the script runnable (and CI-smokeable)
without it.

Run:  horovodrun -np 4 python examples/torch_synthetic_benchmark.py
"""

import argparse
import os
import sys
import timeit

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import torch  # noqa: E402
import torch.nn as nn  # noqa: E402

import horovod_tpu.torch as hvd  # noqa: E402


class TinyNet(nn.Module):
    """Small conv net standing in for torchvision models (CPU-torch
    image; ResNet-50 at the reference protocol would take hours/iter)."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2d(3, 16, 7, stride=4, padding=3), nn.ReLU(),
            nn.Conv2d(16, 32, 3, stride=2, padding=1), nn.ReLU(),
            nn.AdaptiveAvgPool2d(4))
        self.fc = nn.Linear(32 * 16, num_classes)

    def forward(self, x):
        x = self.features(x)
        return self.fc(x.flatten(1))


def build_model(name: str):
    if name == "tiny":
        return TinyNet()
    try:
        import torchvision.models as models
    except ImportError:
        raise SystemExit(
            f"--model {name} needs torchvision (not installed); "
            "use --model tiny")
    return models.__dict__[name]()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="tiny",
                   help='"tiny" or a torchvision model name, e.g. resnet50')
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--image-size", type=int, default=224)
    p.add_argument("--num-warmup-batches", type=int, default=10)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--fp16-allreduce", action="store_true")
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(42)
    torch.set_num_threads(max(1, (os.cpu_count() or 1) // hvd.local_size()))

    model = build_model(args.model)
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    optimizer = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.01),
        named_parameters=model.named_parameters(),
        compression=compression)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(optimizer, root_rank=0)

    data = torch.randn(args.batch_size, 3, args.image_size, args.image_size)
    target = torch.randint(0, 1000, (args.batch_size,))
    loss_fn = nn.CrossEntropyLoss()

    def benchmark_step():
        optimizer.zero_grad()
        loss_fn(model(data), target).backward()
        optimizer.step()

    if hvd.rank() == 0:
        print(f"Model: {args.model}, batch size {args.batch_size} "
              f"per process, {hvd.size()} process(es)")
    for _ in range(args.num_warmup_batches):
        benchmark_step()

    img_secs = []
    for i in range(args.num_iters):
        dt = timeit.timeit(benchmark_step,
                           number=args.num_batches_per_iter)
        img_sec = args.batch_size * args.num_batches_per_iter / dt
        if hvd.rank() == 0:
            print(f"Iter #{i}: {img_sec:.1f} img/sec per process")
        img_secs.append(img_sec)

    mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
    total = float(hvd.allreduce(np.array([mean], np.float64), op=hvd.Sum,
                                name="bench.total")[0])
    if hvd.rank() == 0:
        print(f"Img/sec per process: {mean:.1f} +- {conf:.1f}")
        print(f"Total img/sec on {hvd.size()} process(es): {total:.1f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
