"""Spark ML estimator — the reference's ``examples/spark/pytorch/
pytorch_spark_mnist.py`` flow in this package's idiom, on a synthetic
regression DataFrame.

``TorchEstimator.fit(df)`` stages parquet shards FROM THE EXECUTORS
through the Store (the driver never materializes the DataFrame),
trains across the executors with the eager allreduce tier, and
returns a model transformer; ``validation=`` holds rows out and
``model.history`` carries per-epoch train/val loss.

Run inside a Spark session: ``spark-submit examples/
spark_torch_estimator.py`` (needs pyspark; any shared store path or
s3/gs/hdfs URL works for --store).
"""

import argparse
import sys
import tempfile


def main():
    try:
        from pyspark.sql import SparkSession
    except ImportError:
        print("pyspark is not installed — run this under spark-submit "
              "or `pip install pyspark`. The estimator itself is "
              "exercised without Spark in tests/test_integrations.py.")
        return 0

    import torch

    from horovod_tpu.spark import Store, TorchEstimator

    ap = argparse.ArgumentParser()
    ap.add_argument("--store", default=None,
                    help="staging prefix (shared FS or fsspec URL; "
                         "default: a fresh temp dir)")
    ap.add_argument("--num-proc", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=20)
    args = ap.parse_args()
    store_path = args.store or tempfile.mkdtemp()

    spark = SparkSession.builder.getOrCreate()
    rows = [(float(i) / 100, 2.0 * i / 100 - 1.0) for i in range(1000)]
    df = spark.createDataFrame(rows, ["x", "y"])

    est = TorchEstimator(
        model=torch.nn.Linear(1, 1),
        optimizer=lambda p: torch.optim.SGD(p, lr=0.1),
        loss=torch.nn.functional.mse_loss,
        feature_cols=["x"], label_cols=["y"],
        store=Store.create(store_path),
        num_proc=args.num_proc, epochs=args.epochs, batch_size=32,
        validation=0.2)
    model = est.fit(df)
    print(f"run_id={model.run_id}")
    for m in model.history[-3:]:
        print(f"epoch {m['epoch']:3d}  train {m['train_loss']:.4f}  "
              f"val {m['val_loss']:.4f}")
    pred = model.transform(df.limit(5))
    pred.show()
    return 0


if __name__ == "__main__":
    sys.exit(main())
