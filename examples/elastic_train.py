"""Elastic fault-tolerant training example.

The shape of the reference's elastic examples
(``examples/elastic/pytorch/pytorch_mnist_elastic.py``): wrap the
training loop in ``@hvd.elastic.run`` with a committed ``State`` —
when workers are added or removed (discovery change) or a worker dies
mid-batch (``HorovodInternalError``), survivors restore the last
committed state, re-rendezvous with the new world, and resume from the
committed batch instead of restarting.

Run with scripted discovery (hosts may change between polls):

    horovodrun -np 2 --min-np 1 --max-np 4 \
        --host-discovery-script ./my_discovery.sh \
        python examples/elastic_train.py

or on a Ray cluster:

    from horovod_tpu.ray import ElasticRayExecutor
    ElasticRayExecutor(min_np=2, max_np=8).run(
        ["python", "examples/elastic_train.py"])
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
import horovod_tpu.elastic as elastic  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=50)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    hvd.init()

    rng = np.random.RandomState(0)
    w_true = rng.randn(8)

    # Everything that must survive a membership change lives in the
    # State: it is saved on commit(), restored after a failure, and
    # synced (broadcast from rank 0) after every re-rendezvous.
    state = elastic.ObjectState(batch=0, w=np.zeros(8))

    @elastic.run
    def train(state):
        while state.batch < args.batches:
            x = rng.randn(32, 8)
            err = x @ state.w - x @ w_true
            grad = x.T @ err / len(x)
            # Averaged across however many ranks currently exist.
            grad = hvd.allreduce(grad.astype(np.float32),
                                 name=f"g.{state.batch % 2}")
            state.w = state.w - args.lr * np.asarray(grad, np.float64)
            state.batch += 1
            if state.batch % 10 == 0:
                state.commit()   # checkpoint + host-change check
                if hvd.rank() == 0:
                    loss = float(np.mean((state.w - w_true) ** 2))
                    print(f"batch {state.batch}: size={hvd.size()} "
                          f"loss={loss:.5f}", flush=True)
        return state.w

    w = train(state)
    if hvd.rank() == 0:
        print(f"FINAL err={float(np.mean((w - w_true) ** 2)):.6f}",
              flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
