"""Adasum curve fitting — the reference's ``examples/adasum/
adasum_small_model.py`` scenario in this package's idiom.

Each rank draws differently-seeded noisy samples of the same cubic;
``DistributedOptimizer(op=hvd.Adasum)`` combines the per-rank
gradients with Adasum's orthogonality-aware weighting (the update
keeps the components ranks AGREE on at full strength instead of
averaging them down), so the fit converges on the shared curve.

Run: ``horovodrun -np 2 python examples/adasum_fit.py``
"""

import argparse

import numpy as np
import torch

import horovod_tpu.torch as hvd


def target(x):
    return 10 * x ** 3 + 5 * x ** 2 - 20 * x - 5


class Cubic(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.coef = torch.nn.Parameter(torch.tensor([1.0, -1.0, 1.0]))

    def forward(self, x):
        return (10 * x ** 3 + self.coef[0] * x ** 2
                + self.coef[1] * x + self.coef[2])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=120)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--samples", type=int, default=64)
    args = ap.parse_args()

    hvd.init()
    torch.manual_seed(1234)               # identical initial model
    rng = np.random.RandomState(1 + hvd.rank())  # per-rank data

    model = Cubic()
    opt = torch.optim.SGD(model.parameters(), lr=args.lr)
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters(), op=hvd.Adasum)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    x = torch.tensor(rng.uniform(-1, 1, args.samples), dtype=torch.float32)
    y = torch.tensor(target(x.numpy())
                     + rng.normal(0, 0.1, args.samples), dtype=torch.float32)

    first = None
    for step in range(args.steps):
        opt.zero_grad()
        loss = torch.nn.functional.mse_loss(model(x), y)
        loss.backward()
        opt.step()
        if first is None:
            first = float(loss)
        if step % 20 == 0 and hvd.rank() == 0:
            print(f"step {step:3d}  loss {float(loss):.4f}", flush=True)

    print(f"RANK {hvd.rank()} first={first:.4f} final={float(loss):.4f} "
          f"coef={model.coef.detach().numpy().round(2)}", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
