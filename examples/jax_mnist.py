"""Distributed JAX training example — MNIST semantics on the eager
tier (process-per-rank, the Horovod model).

The JAX analog of the reference's ``examples/pytorch/pytorch_mnist.py``
using the ``horovod_tpu.jax`` binding: ``hvd.init``, shard data by
rank, take gradients with :func:`distributed_value_and_grad` (the
``DistributedGradientTape`` analog — gradients come back
already averaged across ranks), apply them with optax, broadcast
initial parameters from rank 0, and average the eval metric.

For the in-jit SPMD tier (single process driving a whole TPU mesh —
the idiomatic high-performance path), see
``horovod_tpu.models.make_train_step``.

Run:  horovodrun -np 4 python examples/jax_mnist.py --epochs 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import horovod_tpu.jax as hvd  # noqa: E402
from horovod_tpu.callbacks import average_metrics  # noqa: E402


def make_data(rank, size, n=2048, key=0):
    """Synthetic MNIST-shaped data (hermetic), sharded by rank."""
    rng = np.random.RandomState(key)
    x = rng.randn(n, 784).astype(np.float32)
    w_true = rng.randn(784, 10).astype(np.float32)
    y = (x @ w_true + 0.3 * np.tanh(x[:, :10])).argmax(1)
    shard = slice(rank * (n // size), (rank + 1) * (n // size))
    return x[shard], y[shard]


def init_params(key):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (784, 128)) * 784 ** -0.5,
        "b1": jnp.zeros((128,)),
        "w2": jax.random.normal(k2, (128, 10)) * 128 ** -0.5,
        "b2": jnp.zeros((10,)),
    }


def loss_fn(params, x, y):
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    args = ap.parse_args()

    hvd.init()
    r, s = hvd.rank(), hvd.size()
    x, y = make_data(r, s)
    x, y = jnp.asarray(x), jnp.asarray(y)

    params = init_params(jax.random.PRNGKey(1234 + r))  # deliberately
    # divergent init; the broadcast fixes it (reference example's
    # broadcast_parameters step).
    params = hvd.broadcast_parameters(params, root_rank=0)

    # Scale lr by world size (the reference example's convention).
    opt = optax.adam(args.lr * s)
    opt_state = opt.init(params)

    # Gradients averaged across ranks — DistributedGradientTape analog.
    grad_fn = hvd.distributed_value_and_grad(loss_fn)
    jit_loss = jax.jit(loss_fn)

    steps = len(x) // args.batch_size
    for epoch in range(args.epochs):
        for i in range(steps):
            sl = slice(i * args.batch_size, (i + 1) * args.batch_size)
            loss, grads = grad_fn(params, x[sl], y[sl])
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        metrics = average_metrics(
            {"loss": float(jit_loss(params, x, y))}, name=f"ep.{epoch % 2}")
        if r == 0:
            print(f"epoch {epoch}: mean loss {metrics['loss']:.4f}",
                  flush=True)

    final = average_metrics({"loss": float(jit_loss(params, x, y))})
    if r == 0:
        print(f"FINAL loss={final['loss']:.4f}", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
